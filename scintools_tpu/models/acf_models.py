"""Closed-form ACF models for scintillation-parameter fitting.

Reference: scint_models.py:27-105.  There the models are lmfit residual
callbacks mutating ``model[0]``; here they are pure functions of
``(x, params)`` that evaluate on numpy *or* jax arrays (pass ``xp``), so the
same code serves the scipy least-squares CPU path and the vmapped
fixed-iteration LM on TPU, including reverse-mode differentiation.

Conventions preserved from the reference:
* ``tau`` is the 1/e timescale, ``dnu`` the half-power bandwidth
  (hence the ``dnu/log(2)`` scale inside the exponential,
  scint_models.py:73);
* a white-noise spike ``wn`` is added to the zero-lag sample only
  (scint_models.py:48,74);
* models are multiplied by the triangle taper ``1 - x/max(x)``, the
  finite-scan bias of the ACF estimate (scint_models.py:50,76).
"""

from __future__ import annotations

import numpy as np


def tau_acf_model(x, tau, amp, wn, alpha=5 / 3, xp=np):
    """Time-axis ACF cut model (scint_models.py:27-52)."""
    model = amp * xp.exp(-(x / tau) ** alpha)
    model = model + wn * (xp.arange(x.shape[0]) == 0)
    return model * (1 - x / xp.max(x))


def dnu_acf_model(x, dnu, amp, wn, xp=np):
    """Frequency-axis ACF cut model (scint_models.py:55-78)."""
    model = amp * xp.exp(-x / (dnu / np.log(2)))
    model = model + wn * (xp.arange(x.shape[0]) == 0)
    return model * (1 - x / xp.max(x))


def scint_acf_model(x_t, x_f, tau, dnu, amp, wn, alpha=5 / 3, xp=np):
    """Joint model over concatenated (time-cut, frequency-cut) data
    (scint_models.py:81-105).  Returns the concatenated model vector."""
    mt = tau_acf_model(x_t, tau, amp, wn, alpha, xp=xp)
    mf = dnu_acf_model(x_f, dnu, amp, wn, xp=xp)
    return xp.concatenate([mt, mf])


def scint_acf_model_cat(x, is_t, spike, xmax, tau, dnu, amp, wn,
                        alpha=5 / 3, xp=np):
    """:func:`scint_acf_model` on ONE pre-concatenated lag axis — the
    shape-stable form the split pipeline's fitter unit compiles once
    for every observing grid.

    ``x`` is the concatenated (time-cut, frequency-cut) lag vector
    (tail-padded to a closed rung length by the front-end), ``is_t``
    selects the time part, ``spike`` is 1.0 at each part's zero-lag
    sample (the white-noise spike positions), and ``xmax`` carries each
    part's own lag maximum (the triangle-taper scale — a per-part
    reduction the concatenated form cannot recompute).  Element-for-
    element identical to the concat of :func:`tau_acf_model` /
    :func:`dnu_acf_model` (same operation order per element, so the
    split fit is bit-identical — tested in tests/test_split_programs).
    """
    mt = amp * xp.exp(-(x / tau) ** alpha)
    mf = amp * xp.exp(-x / (dnu / np.log(2)))
    model = xp.where(is_t, mt, mf) + wn * spike
    return model * (1 - x / xmax)


def mirror_spectrum(y, xp=np):
    """Mirror a positive-lag function to a symmetric one and return the
    real FFT's positive half — the ACF->power-spectrum transform used by
    every *_sspec_model AND by the spectral-domain fitter's data side
    (they must share this construction to live on the same grid)."""
    sym = xp.concatenate([y, y[::-1]])[: 2 * y.shape[0] - 1]
    return xp.real(xp.fft.fft(sym))[: y.shape[0]]


def tau_sspec_model(x, tau, amp, wn, alpha=5 / 3, xp=np):
    """Fourier-domain (power spectrum) counterpart of tau_acf_model.

    The reference's version is broken — it calls the numpy *module*
    ``np.fft(model)`` (scint_models.py:142) — so this is the repaired
    semantics it intended: mirror the ACF model to a symmetric function and
    take the real FFT, keeping the positive-lag half.
    """
    model = tau_acf_model(x, tau, amp, wn, alpha, xp=xp)
    return mirror_spectrum(model, xp=xp)


def dnu_sspec_model(x, dnu, amp, wn, xp=np):
    """Fourier-domain counterpart of dnu_acf_model (reference stub at
    scint_models.py:149-171, completed here)."""
    model = dnu_acf_model(x, dnu, amp, wn, xp=xp)
    return mirror_spectrum(model, xp=xp)


def scint_sspec_model(x_t, x_f, tau, dnu, amp, wn, alpha=5 / 3, xp=np):
    """Joint Fourier-domain model (reference stub at scint_models.py:174-188,
    completed here)."""
    mt = tau_sspec_model(x_t, tau, amp, wn, alpha, xp=xp)
    mf = dnu_sspec_model(x_f, dnu, amp, wn, xp=xp)
    return xp.concatenate([mt, mf])


def scint_acf_model_2d(x_t, x_f, tau, dnu, amp, wn, alpha=5 / 3,
                       tilt=0.0, tmax=None, fmax=None, xp=np):
    """2-D ACF model over signed (time, frequency) lags — the model the
    reference declares but leaves empty (``scint_acf_model_2D``,
    scint_models.py:108-112).

    Design (ours, consistent with the 1-D cuts): stretched-exponential
    temporal decorrelation sheared by a phase-gradient ``tilt`` (s/MHz —
    refraction displaces the scintle pattern linearly in time per unit
    frequency), exponential frequency decorrelation with half-power
    bandwidth ``dnu``, a zero-lag white-noise spike, and the separable
    finite-scan triangle taper.  At ``x_f=0`` / ``x_t=0`` it reduces to
    :func:`tau_acf_model` / :func:`dnu_acf_model`.

    x_t: [nt] signed time lags (s); x_f: [nf] signed frequency lags (MHz).
    ``tmax``/``fmax`` are the taper scales — the FULL scan duration and
    bandwidth (they default to the lag extent, which is only correct when
    the lags span the whole scan; pass them explicitly when fitting a
    cropped window).  Returns [nf, nt].
    """
    t = x_t[None, :]
    f = x_f[:, None]
    tmax = xp.max(xp.abs(x_t)) if tmax is None else tmax
    fmax = xp.max(xp.abs(x_f)) if fmax is None else fmax
    model = amp * xp.exp(-(xp.abs(t - tilt * f) / tau) ** alpha
                         - xp.abs(f) * np.log(2) / dnu)
    model = model + wn * ((t == 0) & (f == 0))
    taper = (1 - xp.abs(t) / tmax) * (1 - xp.abs(f) / fmax)
    return model * taper
