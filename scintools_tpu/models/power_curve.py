"""Arc power-curve template.

Reference: ``arc_power_curve`` (scint_models.py:191-201) — an EMPTY stub
there (``model = []``); the docstring promises "a template for the power
curve in secondary spectrum vs sqrt(curvature) or normalised fdop".
Implemented for real here (SURVEY.md §2.2): the delay-scrunched power
profile decays as a power law above a noise floor, so the template in
linear power is ``amp * |x|^(-index) + floor``, evaluated in the dB
space the profiles are measured in (norm_sspec's ``powerspec`` output is
``nanmean`` of dB rows, plotted log-log vs sqrt(tdel) —
dynspec.py:863,728-735).

``arc_power_curve`` keeps the reference's residual calling convention
(params, xdata, ydata, weights) so lmfit-style callers port directly,
while :func:`fit_arc_power_curve` drives it with the framework's
fixed-iteration LM over an ArcFit/NormSspec profile.
"""

from __future__ import annotations

import numpy as np

__all__ = ["arc_power_curve_model", "arc_power_curve",
           "fit_arc_power_curve"]


def arc_power_curve_model(x, amp, index, floor, xp=np):
    """Template power curve in dB vs sqrt(curvature) or normalised fdop:
    a power-law decay ``amp * |x|^(-index)`` over a noise floor, in dB.

    ``amp``/``floor`` are linear powers (floor >= 0); x must be > 0
    (profiles are measured on positive sqrt-eta / |fdop| grids).
    """
    return 10.0 * xp.log10(amp * xp.abs(x) ** (-index) + floor)


def arc_power_curve(params, xdata, ydata=None, weights=None, xp=np):
    """Reference-signature entry point (scint_models.py:191-201).

    ``params`` is any mapping with keys ``amp``, ``index``, ``floor``
    (an lmfit ``Parameters`` works via its mapping interface).  With
    ``ydata`` given, returns the weighted residual ``(ydata - model) *
    weights`` as the reference's residual convention promises; without
    it, returns the model template itself.
    """
    amp, index, floor = (params["amp"], params["index"], params["floor"])
    try:  # lmfit Parameter objects carry .value
        amp, index, floor = amp.value, index.value, floor.value
    except AttributeError:
        pass
    model = arc_power_curve_model(xdata, amp, index, floor, xp=xp)
    if ydata is None:
        return model
    if weights is None:
        weights = xp.ones_like(xp.asarray(ydata))
    return (ydata - model) * weights


def fit_arc_power_curve(x, power_db, steps: int = 40, backend="numpy"):
    """Fit the power-curve template to a measured profile.

    ``x`` is the profile's abscissa (sqrt(eta) or normalised fdop, > 0);
    ``power_db`` the mean power in dB (e.g. ``NormSspec.powerspec`` vs
    ``sqrt(tdel)``, or an ``ArcFit.profile_power`` vs
    ``sqrt(profile_eta)``).  NaN bins are dropped.  Returns
    ``(params, stderr)`` with params ``[amp, index, floor]``.
    """
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(power_db, dtype=np.float64)
    ok = np.isfinite(x) & np.isfinite(y) & (x > 0)
    if ok.sum() < 4:
        raise ValueError(f"power-curve fit needs >= 4 finite bins with "
                         f"x > 0, got {int(ok.sum())}")
    from ..backend import resolve

    backend = resolve(backend)
    x, y = x[ok], y[ok]
    # init: log-log slope for the index, head/tail powers for amp/floor
    ylin = 10.0 ** (y / 10.0)
    lo = float(np.percentile(ylin, 5))
    slope = np.polyfit(np.log10(x), y / 10.0, 1)[0]
    p0 = np.array([max(ylin.max() * x.min() ** max(-slope, 0.0), 1e-12),
                   max(-slope, 0.1), max(lo, 1e-12)])
    lb = np.array([1e-300, 0.0, 0.0])
    ub = np.array([np.inf, 20.0, np.inf])

    if backend == "jax":
        import jax.numpy as jnp

        from ..fit.lm import lm_fit_jax

        def resid(p, xj, yj):
            return yj - arc_power_curve_model(xj, p[0], p[1], p[2],
                                              xp=jnp)

        res = lm_fit_jax(resid, p0, bounds=(lb, ub),
                         args=(jnp.asarray(x), jnp.asarray(y)),
                         steps=steps)
    else:
        from ..fit.lm import least_squares_numpy

        def resid(p, xn, yn):
            return yn - arc_power_curve_model(xn, p[0], p[1], p[2])

        res = least_squares_numpy(resid, p0, bounds=(lb, ub),
                                  args=(x, y))
    return np.asarray(res.params), np.asarray(res.stderr)
