"""Parabola peak fitting with error propagation.

Reference: ``fit_parabola``/``fit_log_parabola`` (scint_models.py:216-263):
scale x by 1000/ptp, degree-2 polyfit with covariance, peak at -b/2a with
error propagated from the parameter covariance; the log variant fits in
log(x) and exponentiates.

Implemented as explicit degree-2 least squares (Vandermonde normal solve)
with numpy's polyfit covariance scaling ``resid / (n - order - 2)``
(asserted equal to ``np.polyfit(cov=True)`` in tests), so the same code
runs under numpy and jax and vmaps over batches of profiles.
"""

from __future__ import annotations

import numpy as np


def polyfit2_cov(x, y, w=None, xp=np):
    """Degree-2 least squares returning (coeffs [a,b,c], cov [3,3]) with
    np.polyfit's covariance scaling.

    ``w`` is an optional 0/1 mask enabling fixed-shape windowed fits under
    jit: with binary weights the normal equations, residual and count reduce
    exactly to the subset fit the reference does by slicing."""
    V = xp.stack([x ** 2, x, xp.ones_like(x)], axis=-1)  # [n, 3]
    if w is None:
        n = x.shape[0]
        G = V.T @ V
        rhs = V.T @ y
    else:
        n = xp.sum(w)
        G = V.T @ (V * w[:, None])
        rhs = V.T @ (w * y)
    coeffs = xp.linalg.solve(G, rhs)
    r2 = (y - V @ coeffs) ** 2
    resid = xp.sum(r2 if w is None else w * r2)
    # np.polyfit(cov=True) default scaling in numpy 2.x: chi2/dof with
    # dof = n - (deg+1)  (asserted equal to np.polyfit in tests)
    scale = resid / (n - 3)
    cov = xp.linalg.inv(G) * scale
    return coeffs, cov


def masked_ptp(x, w, xp=np):
    inf = xp.asarray(np.inf, dtype=x.dtype)
    return (xp.max(xp.where(w > 0, x, -inf))
            - xp.min(xp.where(w > 0, x, inf)))


def fit_parabola_vertex(x, y, w=None, xp=np):
    """Degree-2 vertex fit returning ``(a, yfit, peak, peak_error)``
    where ``a`` is the quadratic coefficient in the pre-scaled frame
    (its sign decides forward/backward opening) — the shared core of
    ``fit_parabola`` and the fast arc tail's direct coefficient check,
    so the 1000/ptp pre-scaling and the vertex error propagation exist
    exactly once."""
    ptp = (xp.max(x) - xp.min(x)) if w is None else masked_ptp(x, w, xp)
    xs = x * (1000.0 / ptp)
    coeffs, cov = polyfit2_cov(xs, y, w=w, xp=xp)
    a, b, c = coeffs[0], coeffs[1], coeffs[2]
    yfit = a * xs ** 2 + b * xs + c
    aerr = xp.abs(cov[0, 0]) ** 0.5
    berr = xp.abs(cov[1, 1]) ** 0.5
    peak = -b / (2 * a)
    peak_error = xp.sqrt(berr ** 2 * (1 / (2 * a)) ** 2
                         + aerr ** 2 * (b / 2) ** 2)
    return a, yfit, peak * (ptp / 1000.0), peak_error * (ptp / 1000.0)


def fit_log_parabola_vertex(x, y, w=None, xp=np):
    """``fit_log_parabola``'s vertex with the quadratic coefficient
    exposed: ``(a, yfit, peak, peak_error)`` after the reference's
    double pre-scaling and exp conversion (scint_models.py:245-263).

    Mirrors the reference's double pre-scaling: it hands the vertex fit
    ``logx*(1000/ptp)`` (rescaled again internally), so the fitted peak
    is in those scaled units and converts back via
    ``exp(peak*ptp/1000)`` (scint_models.py:253-259).
    """
    logx = xp.log(x)
    ptp = ((xp.max(logx) - xp.min(logx)) if w is None
           else masked_ptp(logx, w, xp))
    xs = logx * (1000.0 / ptp)
    a, yfit, peak, peak_error = fit_parabola_vertex(xs, y, w=w, xp=xp)
    frac_error = peak_error / peak
    peak = xp.exp(peak * ptp / 1000.0)
    return a, yfit, peak, frac_error * peak


def fit_parabola(x, y, w=None, xp=np):
    """Return (yfit, peak, peak_error) — reference semantics
    (scint_models.py:216-242) including the 1000/ptp pre-scaling."""
    _, yfit, peak, peak_error = fit_parabola_vertex(x, y, w=w, xp=xp)
    return yfit, peak, peak_error


def fit_log_parabola(x, y, w=None, xp=np):
    """Parabola in log(x); peak exponentiated, fractional error
    (scint_models.py:245-263).  Delegates to
    :func:`fit_log_parabola_vertex` so the double pre-scaling exists
    exactly once."""
    _, yfit, peak, peak_error = fit_log_parabola_vertex(x, y, w=w, xp=xp)
    return yfit, peak, peak_error
