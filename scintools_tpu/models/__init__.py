from .acf_models import (dnu_acf_model, dnu_sspec_model,  # noqa: F401
                         scint_acf_model, scint_sspec_model, tau_acf_model,
                         tau_sspec_model)
from .parabola import (fit_log_parabola, fit_parabola, masked_ptp,  # noqa: F401
                       polyfit2_cov)
from .power_curve import (arc_power_curve, arc_power_curve_model,  # noqa: F401
                          fit_arc_power_curve)
from .velocity import (arc_curvature_model, arc_curvature_residuals,  # noqa: F401
                       effective_velocity_annual, thin_screen_veff)
