"""Persistent fixed-cost amortization: XLA compile cache + AOT step export.

BENCH_r05 measured 324.7 s of XLA compilation against 0.54 s of useful
device time — a fresh process is >99.8 % fixed cost.  Two mechanisms,
layered (the compile-cache discipline GPU pulsar pipelines use to hide
host costs behind the FFT engine — arXiv:1711.10855, arXiv:1804.05335):

1. **Persistent XLA compilation cache** (:func:`enable_persistent_cache`)
   — wires ``jax_compilation_cache_dir`` so XLA executables are
   deserialized from disk instead of recompiled.  Directory from
   ``SCINT_COMPILE_CACHE`` (default ``~/.cache/scintools_tpu/xla``;
   ``0``/``off`` disables).  Min-compile-time gating keeps trivial
   programs from spamming the disk.
2. **AOT export of the jit'd pipeline step** (:func:`export_step` /
   :func:`load_step`) — ``jax.export`` StableHLO artifacts keyed on
   (freqs/times digest, PipelineConfig, mesh shape, batch shape, dtype,
   jax/backend version, x64 flag), so a fresh process *deserializes* the
   step instead of re-tracing it.  Layer 2 removes the trace+lower cost;
   layer 1 removes the XLA compile cost of the deserialized module
   (warmup compiles exactly the program the loading process will ask
   for, so the persistent-cache fingerprints match).

Artifacts are written by ``scintools-tpu warmup`` (cli.py) and loaded
opportunistically by :func:`scintools_tpu.parallel.run_pipeline`; a
lookup increments the ``compile_cache_hit`` / ``compile_cache_miss``
obs counters so ``trace report`` decomposes cold vs warm starts.

Everything here is host-side file I/O plus jax config; nothing touches
the device except the caller-provided step itself.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

from . import faults, obs

ENV_VAR = "SCINT_COMPILE_CACHE"
DEFAULT_DIR = "~/.cache/scintools_tpu/xla"
_DISABLED_VALUES = ("", "0", "off", "none", "disabled", "false")
# artifact format version: bump to invalidate every existing artifact
_FORMAT = 1


def cache_dir() -> str | None:
    """Resolved cache directory, or None when disabled via env."""
    val = os.environ.get(ENV_VAR)
    if val is not None and val.strip().lower() in _DISABLED_VALUES:
        return None
    return os.path.expanduser(val or DEFAULT_DIR)


def aot_dir() -> str | None:
    d = cache_dir()
    return None if d is None else os.path.join(d, "aot")


def enable_persistent_cache(path: str | None = None) -> str | None:
    """Wire jax's persistent compilation cache to ``path`` (default: the
    env-resolved :func:`cache_dir`).  Returns the directory in effect,
    or None when disabled.  Idempotent; an ambient
    ``JAX_COMPILATION_CACHE_DIR`` (or an explicit earlier wiring) wins
    over the default so bench's repo-local ``.jax_cache`` contract and
    user overrides are respected.  Min-compile-time gating (1 s) keeps
    sub-second programs out of the cache."""
    d = path if path is not None else cache_dir()
    if d is None:
        return None
    try:
        import jax

        ambient = (os.environ.get("JAX_COMPILATION_CACHE_DIR")
                   or getattr(jax.config, "jax_compilation_cache_dir",
                              None))
        if path is None and ambient:
            d = ambient
        os.makedirs(d, exist_ok=True)
        # export to children (warmup/process pairs, bench subprocesses)
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", d)
        jax.config.update("jax_compilation_cache_dir", d)
        if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
        if "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES" not in os.environ:
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", 0)
        return d
    except Exception:
        # the cache is an optimisation: never fail the pipeline over it
        return None


_SERIALIZATION_DONE = False


def _register_serialization() -> None:
    """Register the pipeline's custom result pytrees with jax.export so
    the step's out_tree serializes (idempotent; re-registration of an
    already-known node is not an error here)."""
    global _SERIALIZATION_DONE
    if _SERIALIZATION_DONE:
        return
    from jax import export

    from .data import ArcFit, ScintParams, SecSpec
    from .parallel.driver import PipelineResult

    for cls in (PipelineResult, ScintParams, ArcFit, SecSpec):
        try:
            export.register_pytree_node_serialization(
                cls, serialized_name=f"scintools_tpu.{cls.__name__}",
                serialize_auxdata=lambda aux: json.dumps(aux).encode(),
                deserialize_auxdata=lambda b: json.loads(b.decode()))
        except ValueError:
            pass  # already registered (e.g. two drivers in one process)
    _SERIALIZATION_DONE = True


def _mesh_desc(mesh) -> tuple | None:
    if mesh is None:
        return None
    return tuple((str(name), int(size))
                 for name, size in dict(mesh.shape).items())


_SOURCE_FP: str | None = None


def _source_fingerprint() -> str:
    """Digest of the package's own source tree (computed once per
    process).  Any code change to scintools_tpu changes the traced
    program in ways the config/axes key cannot see — an upgraded
    package must never silently serve a stale artifact."""
    global _SOURCE_FP
    if _SOURCE_FP is None:
        import glob as _glob

        pkg = os.path.dirname(os.path.abspath(__file__))
        h = hashlib.sha256()
        for py in sorted(_glob.glob(os.path.join(pkg, "**", "*.py"),
                                    recursive=True)):
            # package-relative path: byte-identical code must key the
            # same from any checkout/install location
            h.update(os.path.relpath(py, pkg).encode())
            try:
                with open(py, "rb") as fh:
                    h.update(fh.read())
            except OSError:  # pragma: no cover
                continue
        _SOURCE_FP = h.hexdigest()[:16]
    return _SOURCE_FP


def step_key(freqs, times, config, mesh, chan_sharded: bool,
             batch_shape, dtype, donate: bool = False) -> str:
    """Content-hash key of one compiled step signature.

    Anything that changes the traced program (or the validity of its
    serialized StableHLO) is in the key: the exact frequency/time axes,
    the full PipelineConfig, mesh shape + channel sharding, the padded
    batch shape, the canonical input dtype, input donation, the x64
    flag, the jax / jaxlib / backend-platform versions, and a digest of
    this package's own source tree (any scintools_tpu code change can
    change the traced program, so it must invalidate every artifact)."""
    import jax
    import jaxlib

    f = np.ascontiguousarray(np.asarray(freqs, dtype=np.float64))
    t = np.ascontiguousarray(np.asarray(times, dtype=np.float64))
    desc = repr((
        _FORMAT,
        f.shape, t.shape,
        repr(config),
        _mesh_desc(mesh), bool(chan_sharded),
        tuple(int(s) for s in batch_shape),
        str(jax.dtypes.canonicalize_dtype(dtype)),
        bool(donate),
        bool(jax.config.jax_enable_x64),
        jax.__version__, jaxlib.__version__, jax.default_backend(),
        _source_fingerprint(),
    ))
    h = hashlib.sha256()
    h.update(f.tobytes())
    h.update(t.tobytes())
    h.update(desc.encode())
    return h.hexdigest()[:32]


def artifact_path(key: str) -> str | None:
    d = aot_dir()
    return None if d is None else os.path.join(d, key + ".jaxexport")


def export_step(step, batch_shape, dtype, key: str) -> str | None:
    """AOT-lower ``step`` for one input signature and persist the
    serialized jax.export artifact under ``key``.  Returns the artifact
    path, or None when the cache is disabled or export is unsupported
    for this step (e.g. an exotic sharding) — failure never propagates,
    the jit path simply stays the fallback."""
    path = artifact_path(key)
    if path is None:
        return None
    try:
        import jax
        from jax import export

        _register_serialization()
        spec = jax.ShapeDtypeStruct(
            tuple(int(s) for s in batch_shape),
            jax.dtypes.canonicalize_dtype(dtype))
        data = export.export(step)(spec).serialize()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)  # atomic: concurrent warmups can't tear
        # the file changed: a memoized deserialization of the OLD bytes
        # must not outlive it (warmup --force relies on this)
        _LOADED.pop(path, None)
        return path
    except Exception:
        return None


_PRIMED = False


def _prime_ffi_registrations() -> None:
    """Eagerly register the backend's lazily-registered custom-call
    targets before executing a deserialized module.

    jaxlib registers CPU LAPACK/FFI custom-call targets at LOWERING
    time of the corresponding primitives.  A process that deserializes
    an exported module and executes it WITHOUT ever lowering those
    primitives hits an unregistered custom-call target and — on jaxlib
    0.4.37 CPU — segfaults outright (reproduced: the LM fit's
    ``linalg.solve``; lowering one solve in-process fixes it).  Lower
    millisecond-scale surrogates of every linalg/fft primitive the
    pipeline can embed: trace+lower only — no XLA compile, no
    execution, no device memory."""
    global _PRIMED
    if _PRIMED:
        return
    try:
        import jax
        import jax.numpy as jnp

        spec = jax.ShapeDtypeStruct(
            (3, 3), jax.dtypes.canonicalize_dtype(np.float64))
        for fn in (
            lambda a: jnp.linalg.solve(a, a),
            lambda a: jnp.linalg.eigh(a + a.T)[0],
            lambda a: jnp.linalg.svd(a, compute_uv=False),
            lambda a: jnp.linalg.qr(a)[0],
            lambda a: jnp.linalg.cholesky(a @ a.T + 3.0 * jnp.eye(3)),
            lambda a: jnp.fft.rfft2(a).real + jnp.fft.ifft2(a).real,
        ):
            try:
                jax.jit(fn).lower(spec)
            except Exception:
                continue
        _PRIMED = True
    except Exception:
        pass


# in-process memo of deserialized steps: repeated run_pipeline calls in
# one process reuse ONE jit'd wrapper (and its compiled-executable
# cache) per artifact, mirroring make_pipeline's lru_cache.  Keyed by
# the artifact PATH (not the content key): the same key under a
# different SCINT_COMPILE_CACHE dir is a different artifact, and a
# memo hit must never outlive its file.
_LOADED: dict = {}


def load_step(key: str, count: bool = True):
    """Deserialize the AOT artifact for ``key`` into a jit'd callable,
    or None when absent/unreadable.  Increments ``compile_cache_hit`` /
    ``compile_cache_miss`` (obs counters, no-ops when tracing is off)
    unless ``count=False``.

    The returned callable is ``jax.jit`` of the deserialized module's
    call: its first invocation pays XLA compile of the StableHLO, which
    the persistent compilation cache serves from disk when ``warmup``
    populated it (warmup compiles via this same loader, so the
    fingerprints match)."""
    path = artifact_path(key)
    if path is None:
        return None
    if not os.path.exists(path):
        if count:
            obs.inc("compile_cache_miss")
        return None
    cached = _LOADED.get(path)
    if cached is not None:
        if count:
            obs.inc("compile_cache_hit")
        return cached
    try:
        # chaos site: a corrupt/unreadable artifact must degrade to the
        # jit path (counted as a miss), never fail the survey
        faults.check("compile_cache.load")
        import jax
        from jax import export

        _register_serialization()
        _prime_ffi_registrations()
        with open(path, "rb") as fh:
            data = fh.read()
        fn = jax.jit(export.deserialize(data).call)
        _LOADED[path] = fn
        if count:
            obs.inc("compile_cache_hit")
        return fn
    except Exception:
        # corrupt / version-skewed artifact: a key mismatch should have
        # prevented this, but degrade to the jit path rather than fail
        if count:
            obs.inc("compile_cache_miss")
        return None


def plan_steps(epochs, config, mesh=None, chunk: int | None = None,
               pad_chunks: bool = False, batch: int | None = None) -> list:
    """The exact step signatures a ``run_pipeline(epochs, config, mesh,
    chunk=..., pad_chunks=...)`` call will execute, as
    ``[(freqs, times, (b, nf, nt), dtype, chunked), ...]`` — shares the
    driver's bucketing, divisibility padding and chunk math so a warmup
    compiles precisely the programs the survey will ask for.
    ``chunked`` says whether that bucket runs through the chunk loop
    (which decides input donation — part of the cache key).

    ``batch`` overrides each bucket's epoch count (warm up for the
    production survey size from a few template files)."""
    from .parallel import driver as drv
    from .parallel import mesh as mesh_mod

    multiple = 1
    if mesh is not None:
        multiple = mesh.shape[mesh_mod.DATA_AXIS]
    plans = []
    for key, idx in drv._bucket_epochs(epochs).items():
        (nf,), (nt,) = key[0], key[1]
        n = batch if batch is not None else len(idx)
        B = -(-n // multiple) * multiple
        freqs = np.frombuffer(key[2]).reshape(key[0])
        times = np.frombuffer(key[3]).reshape(key[1])
        chunked = chunk is not None and chunk < B
        for b in sorted(drv._step_batch_sizes(B, multiple, chunk,
                                              pad_chunks=pad_chunks)):
            # the staging dtype is the precision policy's transfer dtype
            # (driver.stage_dtype): f64 host staging by default, bf16
            # under precision="bf16_io" — it is part of the step key,
            # so warmup must plan exactly what run_pipeline will stage
            plans.append((freqs, times, (b, nf, nt),
                          drv.stage_dtype(config.precision), chunked))
    return plans
