"""Persistent fixed-cost amortization: XLA compile cache + AOT step export.

BENCH_r05 measured 324.7 s of XLA compilation against 0.54 s of useful
device time — a fresh process is >99.8 % fixed cost.  Three mechanisms,
layered (the compile-cache discipline GPU pulsar pipelines use to hide
host costs behind the FFT engine — arXiv:1711.10855, arXiv:1804.05335):

1. **Persistent XLA compilation cache** (:func:`enable_persistent_cache`)
   — wires ``jax_compilation_cache_dir`` so XLA executables are
   deserialized from disk instead of recompiled.  Directory from
   ``SCINT_COMPILE_CACHE`` (default ``~/.cache/scintools_tpu/xla``;
   ``0``/``off`` disables).  Min-compile-time gating keeps trivial
   programs from spamming the disk; an LRU size cap
   (:func:`enforce_cache_cap`, ``SCINT_COMPILE_CACHE_MAX_MB``) bounds
   growth.  Serves the LIVE jit path: a warmed-then-restarted process
   pays retrace but not compile (the live step's cache fingerprint is
   cross-process stable).
2. **AOT export of the jit'd pipeline step** (:func:`export_step` /
   :func:`load_step`) — ``jax.export`` StableHLO artifacts keyed on
   (freqs/times digest, PipelineConfig, mesh shape, batch shape, dtype,
   jax/backend version, x64 flag), so a fresh process *deserializes*
   the step instead of re-tracing it.  Removes the trace+lower cost
   only: re-lowering a DESERIALIZED module embeds process-history-
   dependent bytes, so its XLA fingerprint is not reliably served by
   layer 1 across processes (measured: ~40 s residual compile at the
   256x256 survey signature).
3. **Serialized executables** (:func:`export_executable`) — the
   COMPILED step itself (``jax.experimental.serialize_executable``),
   keyed identically.  :func:`load_step` prefers this layer: a fresh
   pod deserializes and RUNS — no retrace, no compile (measured
   ~0.3 s at the same signature).  Together with the warm-cache
   artifact (:func:`pack_warm_cache` / :func:`unpack_warm_cache`,
   ``scripts/build_warm_cache.py``) this is the cold-start kill:
   cold pod -> first result in seconds.

Artifacts are written by ``scintools-tpu warmup`` (cli.py) and loaded
opportunistically by :func:`scintools_tpu.parallel.run_pipeline`; a
lookup increments the ``compile_cache_hit`` / ``compile_cache_miss``
obs counters so ``trace report`` decomposes cold vs warm starts.

Everything here is host-side file I/O plus jax config; nothing touches
the device except the caller-provided step itself.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

from . import faults, obs

ENV_VAR = "SCINT_COMPILE_CACHE"
DEFAULT_DIR = "~/.cache/scintools_tpu/xla"
_DISABLED_VALUES = ("", "0", "off", "none", "disabled", "false")
# artifact format version: bump to invalidate every existing artifact
_FORMAT = 1

# cache hygiene: total on-disk size cap with LRU (mtime) eviction —
# the persistent cache grows one file per compiled signature forever
# otherwise.  Env knob in MB; 0/off disables the cap.
CAP_ENV = "SCINT_COMPILE_CACHE_MAX_MB"
DEFAULT_CAP_MB = 4096

# warm-cache artifact manifest, written by pack_warm_cache into the
# cache dir (and into the tarball) so a fresh pod can verify the
# artifact matches its runtime before trusting it
MANIFEST_NAME = "MANIFEST.json"


def cache_dir() -> str | None:
    """Resolved cache directory, or None when disabled via env."""
    val = os.environ.get(ENV_VAR)
    if val is not None and val.strip().lower() in _DISABLED_VALUES:
        return None
    return os.path.expanduser(val or DEFAULT_DIR)


def aot_dir() -> str | None:
    d = cache_dir()
    return None if d is None else os.path.join(d, "aot")


def enable_persistent_cache(path: str | None = None) -> str | None:
    """Wire jax's persistent compilation cache to ``path`` (default: the
    env-resolved :func:`cache_dir`).  Returns the directory in effect,
    or None when disabled.  Idempotent; an ambient
    ``JAX_COMPILATION_CACHE_DIR`` (or an explicit earlier wiring) wins
    over the default so bench's repo-local ``.jax_cache`` contract and
    user overrides are respected.  Min-compile-time gating (1 s) keeps
    sub-second programs out of the cache."""
    d = path if path is not None else cache_dir()
    if d is None:
        return None
    try:
        import jax

        ambient = (os.environ.get("JAX_COMPILATION_CACHE_DIR")
                   or getattr(jax.config, "jax_compilation_cache_dir",
                              None))
        if path is None and ambient:
            d = ambient
        os.makedirs(d, exist_ok=True)
        # export to children (warmup/process pairs, bench subprocesses)
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", d)
        jax.config.update("jax_compilation_cache_dir", d)
        if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
        if "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES" not in os.environ:
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        # the cache is an optimisation: never fail the pipeline over it
        return None
    # hygiene: bound the cache's disk footprint once per process (LRU
    # eviction; outside the try so a typo'd SCINT_COMPILE_CACHE_MAX_MB
    # fails loudly instead of silently disabling the cache).  Cap ONLY
    # the directory this package OWNS — the explicit ``path`` or the
    # SCINT_COMPILE_CACHE resolution — never an ambient
    # JAX_COMPILATION_CACHE_DIR override, which may be a machine-wide
    # cache shared with other jax projects whose files we must not
    # delete.
    global _CAP_ENFORCED
    if not _CAP_ENFORCED:
        # latch only AFTER a successful pass: a caller that catches the
        # ValueError from a typo'd cap, fixes os.environ and retries
        # must not find enforcement permanently disarmed (the same
        # latch-before-success class faults.install_env fixed)
        enforce_cache_cap(path if path is not None else cache_dir())
        _CAP_ENFORCED = True
    return d


_CAP_ENFORCED = False


_SERIALIZATION_DONE = False


def _register_serialization() -> None:
    """Register the pipeline's custom result pytrees with jax.export so
    the step's out_tree serializes (idempotent; re-registration of an
    already-known node is not an error here)."""
    global _SERIALIZATION_DONE
    if _SERIALIZATION_DONE:
        return
    from jax import export

    from .data import ArcFit, ScintParams, SecSpec
    from .parallel.driver import PipelineResult

    for cls in (PipelineResult, ScintParams, ArcFit, SecSpec):
        try:
            export.register_pytree_node_serialization(
                cls, serialized_name=f"scintools_tpu.{cls.__name__}",
                serialize_auxdata=lambda aux: json.dumps(aux).encode(),
                deserialize_auxdata=lambda b: json.loads(b.decode()))
        except ValueError:
            pass  # already registered (e.g. two drivers in one process)
    _SERIALIZATION_DONE = True


def _mesh_desc(mesh) -> tuple | None:
    if mesh is None:
        return None
    return tuple((str(name), int(size))
                 for name, size in dict(mesh.shape).items())


_SOURCE_FP: str | None = None


def _source_fingerprint() -> str:
    """Digest of the package's own source tree (computed once per
    process).  Any code change to scintools_tpu changes the traced
    program in ways the config/axes key cannot see — an upgraded
    package must never silently serve a stale artifact."""
    global _SOURCE_FP
    if _SOURCE_FP is None:
        import glob as _glob

        pkg = os.path.dirname(os.path.abspath(__file__))
        h = hashlib.sha256()
        for py in sorted(_glob.glob(os.path.join(pkg, "**", "*.py"),
                                    recursive=True)):
            # package-relative path: byte-identical code must key the
            # same from any checkout/install location
            h.update(os.path.relpath(py, pkg).encode())
            try:
                with open(py, "rb") as fh:
                    h.update(fh.read())
            except OSError:  # pragma: no cover
                continue
        _SOURCE_FP = h.hexdigest()[:16]
    return _SOURCE_FP


def step_key(freqs, times, config, mesh, chan_sharded: bool,
             batch_shape, dtype, donate: bool = False,
             synth=None, unit: str | None = None) -> str:
    """Content-hash key of one compiled step signature.

    Anything that changes the traced program (or the validity of its
    serialized StableHLO) is in the key: the exact frequency/time axes,
    the full PipelineConfig, mesh shape + channel sharding, the padded
    batch shape, the canonical input dtype, input donation, the x64
    flag, the jax / jaxlib / backend-platform versions, and a digest of
    this package's own source tree (any scintools_tpu code change can
    change the traced program, so it must invalidate every artifact).
    ``synth`` is the synthetic route's generator identity
    (``sim.campaign.generator_id`` — a canonicalised SynthSpec with a
    stable repr): a key-fed generate→analyse program is a different
    executable from the file-fed analyser over the same axes.

    ``unit`` names a split-program unit (ISSUE 14): the split
    front-end keys here as ``unit="front"`` with the fitter-only knobs
    already pinned (``driver._front_config``), so changing a fitter
    knob never invalidates the transform artifacts; the shape-stable
    back-end keys through :func:`split_backend_key` instead — its
    identity holds NO axes at all."""
    import jax
    import jaxlib

    f = np.ascontiguousarray(np.asarray(freqs, dtype=np.float64))
    t = np.ascontiguousarray(np.asarray(times, dtype=np.float64))
    desc = repr((
        _FORMAT,
        f.shape, t.shape,
        repr(config),
        _mesh_desc(mesh), bool(chan_sharded),
        tuple(int(s) for s in batch_shape),
        str(jax.dtypes.canonicalize_dtype(dtype)),
        bool(donate),
        bool(jax.config.jax_enable_x64),
        jax.__version__, jaxlib.__version__, jax.default_backend(),
        _source_fingerprint(),
        repr(synth),
        unit,
    ))
    h = hashlib.sha256()
    h.update(f.tobytes())
    h.update(t.tobytes())
    h.update(desc.encode())
    return h.hexdigest()[:32]


def split_backend_key(back_desc, back_sig) -> str:
    """Content-hash key of the split pipeline's shape-stable BACK-END
    unit (ISSUE 14).  Deliberately axes-free: the identity is the
    fitter-program description (``driver.split_backend_desc`` — alpha,
    lm_steps, profile length, tail knobs ...) plus the canonicalised
    intermediate signature (``_SplitStep.back_sig`` — rung/profile
    lengths and batch size), the x64 flag, the jax/jaxlib/backend
    versions and the package source digest.  Every (nf, nt) whose
    intermediates pad onto the same rungs shares ONE artifact — the
    warmed fitter set covers novel survey shapes."""
    import jax
    import jaxlib

    desc = repr((
        _FORMAT, "split-back",
        tuple(back_desc), tuple(back_sig),
        bool(jax.config.jax_enable_x64),
        jax.__version__, jaxlib.__version__, jax.default_backend(),
        _source_fingerprint(),
    ))
    return hashlib.sha256(desc.encode()).hexdigest()[:32]


def search_key(spec, config, srch, rung: int) -> str:
    """Content-hash key of one compiled acceleration-search signature
    (ISSUE 19).  The bank DIMENSIONS are program statics, so they key
    here alongside the generator identity, the analysis config, the
    batch rung and the environment: a different trial count, delay
    window or pruning envelope is a different executable even over
    identical axes (the ``warmup --search`` label + cache identity)."""
    import dataclasses as _dc

    import jax
    import jaxlib

    from .sim import campaign as _campaign

    desc = repr((
        _FORMAT, "search",
        _campaign.generator_id(spec),
        repr(config),
        _dc.astuple(srch),
        int(rung),
        bool(jax.config.jax_enable_x64),
        jax.__version__, jaxlib.__version__, jax.default_backend(),
        _source_fingerprint(),
    ))
    return hashlib.sha256(desc.encode()).hexdigest()[:32]


def artifact_path(key: str) -> str | None:
    d = aot_dir()
    return None if d is None else os.path.join(d, key + ".jaxexport")


def artifact_exec_path(key: str) -> str | None:
    d = aot_dir()
    return None if d is None else os.path.join(d, key + ".jaxexec")


def export_executable(step, batch_shape, dtype, key: str,
                      sharding=None, spec=None) -> str | None:
    """Compile ``step`` for one input signature and persist the
    COMPILED executable (``jax.experimental.serialize_executable``:
    pickled payload + in/out trees) under ``key`` — the artifact
    :func:`load_step` prefers.

    Why a third layer: a ``jax.export`` StableHLO artifact still pays
    XLA compilation on load, and that compile's persistent-cache
    fingerprint is NOT cross-process stable for a deserialized module
    (re-lowering it embeds process-history-dependent bytes — measured:
    a warmed 256x256 step still cost ~40 s on a fresh pod).  The
    serialized EXECUTABLE skips retrace AND compile entirely: a fresh
    pod deserializes and runs (measured ~0.3 s).  The ``step.lower().
    compile()`` here also lands in the persistent XLA cache under the
    LIVE step's fingerprint, which IS cross-process stable — the
    fallback layer when the executable artifact is absent or
    unreadable.

    The payload is pickle: artifacts are operator-produced trusted
    inputs (the warm-cache manifest verifies provenance/version skew,
    not malice) — never load one from an untrusted source.  Returns
    the artifact path, or None when the cache is disabled or
    serialization is unsupported for this step/backend."""
    path = artifact_exec_path(key)
    if path is None:
        return None
    try:
        import pickle

        import jax
        from jax.experimental import serialize_executable as se

        _register_serialization()
        if spec is None:
            # single dyn-batch input signature; split units pass their
            # own ShapeDtypeStruct pytree via ``spec=`` instead
            spec = jax.ShapeDtypeStruct(
                tuple(int(s) for s in batch_shape),
                jax.dtypes.canonicalize_dtype(dtype), sharding=sharding)
        compiled = step.lower(spec).compile()
        data = pickle.dumps(se.serialize(compiled))
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)
        # memoize the live Compiled under the artifact path: it IS what
        # the file deserializes to, and same-process deserialization of
        # a just-serialized CPU executable can hit an XLA "Symbols not
        # found" collision with the process's own compiled symbols
        # (cross-process loads — the artifact's whole point — are
        # unaffected; verified both directions)
        _LOADED[path] = compiled
        return path
    except Exception:
        return None


def export_step(step, batch_shape, dtype, key: str,
                spec=None) -> str | None:
    """AOT-lower ``step`` for one input signature and persist the
    serialized jax.export artifact under ``key``.  Returns the artifact
    path, or None when the cache is disabled or export is unsupported
    for this step (e.g. an exotic sharding) — failure never propagates,
    the jit path simply stays the fallback."""
    path = artifact_path(key)
    if path is None:
        return None
    try:
        import jax
        from jax import export

        _register_serialization()
        if spec is None:
            spec = jax.ShapeDtypeStruct(
                tuple(int(s) for s in batch_shape),
                jax.dtypes.canonicalize_dtype(dtype))
        data = export.export(step)(spec).serialize()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)  # atomic: concurrent warmups can't tear
        # the file changed: a memoized deserialization of the OLD bytes
        # must not outlive it (warmup --force relies on this)
        _LOADED.pop(path, None)
        return path
    except Exception:
        return None


_PRIMED = False


def _prime_ffi_registrations() -> None:
    """Eagerly register the backend's lazily-registered custom-call
    targets before executing a deserialized module.

    jaxlib registers CPU LAPACK/FFI custom-call targets at LOWERING
    time of the corresponding primitives.  A process that deserializes
    an exported module and executes it WITHOUT ever lowering those
    primitives hits an unregistered custom-call target and — on jaxlib
    0.4.37 CPU — segfaults outright (reproduced: the LM fit's
    ``linalg.solve``; lowering one solve in-process fixes it).  Lower
    millisecond-scale surrogates of every linalg/fft primitive the
    pipeline can embed: trace+lower only — no XLA compile, no
    execution, no device memory."""
    global _PRIMED
    if _PRIMED:
        return
    try:
        import jax
        import jax.numpy as jnp

        spec = jax.ShapeDtypeStruct(
            (3, 3), jax.dtypes.canonicalize_dtype(np.float64))
        for fn in (
            lambda a: jnp.linalg.solve(a, a),
            lambda a: jnp.linalg.eigh(a + a.T)[0],
            lambda a: jnp.linalg.svd(a, compute_uv=False),
            lambda a: jnp.linalg.qr(a)[0],
            lambda a: jnp.linalg.cholesky(a @ a.T + 3.0 * jnp.eye(3)),
            lambda a: jnp.fft.rfft2(a).real + jnp.fft.ifft2(a).real,
        ):
            try:
                jax.jit(fn).lower(spec)
            except Exception:
                continue
        _PRIMED = True
    except Exception:
        pass


# in-process memo of deserialized steps: repeated run_pipeline calls in
# one process reuse ONE jit'd wrapper (and its compiled-executable
# cache) per artifact, mirroring make_pipeline's lru_cache.  Keyed by
# the artifact PATH (not the content key): the same key under a
# different SCINT_COMPILE_CACHE dir is a different artifact, and a
# memo hit must never outlive its file.
_LOADED: dict = {}


def load_step(key: str, count: bool = True):
    """Materialise the warm artifact for ``key``, or None when
    absent/unreadable.  Increments ``compile_cache_hit`` /
    ``compile_cache_miss`` (obs counters, no-ops when tracing is off)
    unless ``count=False``.

    Two layers, preferred in order:

    1. **Serialized executable** (``<key>.jaxexec``,
       :func:`export_executable`) — deserialize_and_load returns a
       ready ``Compiled``: no retrace, no XLA compile (the true warm
       start; measured ~0.3 s at the 256x256 survey signature).
    2. **jax.export StableHLO** (``<key>.jaxexport``,
       :func:`export_step`) — ``jax.jit`` of the deserialized module's
       call: skips retrace, but its first invocation pays XLA compile
       (the persistent cache only sometimes serves it: re-lowering a
       deserialized module embeds process-history-dependent bytes, so
       the fingerprint is not reliably cross-process stable)."""
    epath = artifact_exec_path(key)
    path = artifact_path(key)
    if epath is None and path is None:
        return None
    for p in (epath, path):
        cached = _LOADED.get(p)
        if cached is not None:
            # refresh LRU recency on MEMO hits too: a resident worker
            # serves from _LOADED for days, and its hottest artifact
            # must not age into another process's eviction pass
            _touch(p)
            if count:
                obs.inc("compile_cache_hit")
            return cached
    if epath is not None and os.path.exists(epath):
        _touch(epath)  # LRU recency: a served artifact stays young
        try:
            # chaos site: a corrupt/unreadable artifact must degrade to
            # the jit path (counted as a miss), never fail the survey
            faults.check("compile_cache.load")
            import pickle

            from jax.experimental import serialize_executable as se

            _register_serialization()
            _prime_ffi_registrations()
            with open(epath, "rb") as fh:
                payload, in_tree, out_tree = pickle.load(fh)
            fn = se.deserialize_and_load(payload, in_tree, out_tree)
            _LOADED[epath] = fn
            if count:
                obs.inc("compile_cache_hit")
            return fn
        except Exception:
            pass  # degrade to the StableHLO layer below
    if path is None or not os.path.exists(path):
        if count:
            obs.inc("compile_cache_miss")
        return None
    _touch(path)
    try:
        faults.check("compile_cache.load")
        import jax
        from jax import export

        _register_serialization()
        _prime_ffi_registrations()
        with open(path, "rb") as fh:
            data = fh.read()
        fn = jax.jit(export.deserialize(data).call)
        _LOADED[path] = fn
        if count:
            obs.inc("compile_cache_hit")
        return fn
    except Exception:
        # corrupt / version-skewed artifact: a key mismatch should have
        # prevented this, but degrade to the jit path rather than fail
        if count:
            obs.inc("compile_cache_miss")
        return None


def plan_steps(epochs, config, mesh=None, chunk: int | None = None,
               pad_chunks: bool = False, batch: int | None = None,
               catalog: bool = False, synthetic=None) -> list:
    """The exact step signatures a ``run_pipeline(epochs, config, mesh,
    chunk=..., pad_chunks=...)`` call will execute, as
    ``[(freqs, times, (b, nf, nt), dtype, chunked), ...]`` — shares the
    driver's bucketing, divisibility padding and chunk math so a warmup
    compiles precisely the programs the survey will ask for.
    ``chunked`` says whether that bucket runs through the chunk loop
    (which decides input donation — part of the cache key).

    ``batch`` overrides each bucket's epoch count (warm up for the
    production survey size from a few template files).

    ``catalog=True`` plans the CLOSED bucket catalog instead of this
    survey's raw sizes: every ladder rung per axes bucket
    (scintools_tpu.buckets — ``batch`` overrides the ladder top), plus
    the top rung's chunk-loop variant (donation differs there on TPU).
    A worker warmed this way serves ANY epoch count of these observing
    setups with ``jit_cache_miss == 0`` when the caller canonicalises
    (``run_pipeline(bucket=True)`` / the serve batcher).

    ``synthetic`` (a SynthSpec) plans the zero-H2D campaign route
    instead of file buckets: one axes bucket from the spec, uint32 key
    signatures ``(b, 2+F)``, the same ladder/chunk math — so ``warmup
    --synthetic`` pre-compiles exactly what a served ``simulate`` job
    or ``run_pipeline(synthetic=...)`` will execute (the caller also
    passes the spec's generator identity into :func:`step_key`).

    Under ``config.split_programs`` each planned signature expands to
    TWO artifacts at export time (cmd_warmup): the shape-keyed
    front-end (``step_key(..., unit="front")``) and the axes-free
    fitter back-end (:func:`split_backend_key`) — the plan tuples
    themselves are unchanged, since both units derive from one
    ``make_pipeline`` signature."""
    from .parallel import driver as drv
    from .parallel import mesh as mesh_mod

    multiple = 1
    if mesh is not None:
        multiple = mesh.shape[mesh_mod.DATA_AXIS]
    plans = []
    if synthetic is not None:
        from .sim import campaign

        campaign.validate_spec(synthetic)
        freqs, times = campaign.synth_axes(synthetic)
        sdt = np.dtype(np.uint32)
        width = campaign.stage_width(synthetic)
        n = batch if batch is not None else synthetic.n_epochs
        B = -(-n // multiple) * multiple
        if catalog:
            from . import buckets as buckets_mod

            top = batch
            if top is None and chunk is not None:
                top = drv._adjust_chunk(multiple, chunk)
            ladder = buckets_mod.batch_ladder(multiple, top=top)
            for b in ladder:
                plans.append((freqs, times, (b, width), sdt, False))
            plans.append((freqs, times, (ladder[-1], width), sdt, True))
            return plans
        chunked = chunk is not None and chunk < B
        for b in sorted(drv._step_batch_sizes(B, multiple, chunk,
                                              pad_chunks=pad_chunks)):
            plans.append((freqs, times, (b, width), sdt, chunked))
        return plans
    if catalog:
        from . import buckets as buckets_mod

        sdt = drv.stage_dtype(config.precision)
        # ladder top: an explicit batch wins; else an explicit chunk
        # caps it exactly as run_pipeline(bucket=True, chunk=...) does
        # (adjusted DOWN to a mesh multiple — a warmup must compile the
        # precise signatures a chunk-capped bucketed survey executes)
        top = batch
        if top is None and chunk is not None:
            top = drv._adjust_chunk(multiple, chunk)
        for key in drv._bucket_epochs(epochs):
            (nf,), (nt,) = key[0], key[1]
            freqs = np.frombuffer(key[2]).reshape(key[0])
            times = np.frombuffer(key[3]).reshape(key[1])
            ladder = buckets_mod.batch_ladder(multiple, top=top)
            for b in ladder:
                plans.append((freqs, times, (b, nf, nt), sdt, False))
            # the top rung also runs through the chunk loop (surveys
            # larger than the catalog top), where input donation
            # differs on TPU — its own cache key, warmed explicitly
            plans.append((freqs, times, (ladder[-1], nf, nt), sdt, True))
        return plans
    for key, idx in drv._bucket_epochs(epochs).items():
        (nf,), (nt,) = key[0], key[1]
        n = batch if batch is not None else len(idx)
        B = -(-n // multiple) * multiple
        freqs = np.frombuffer(key[2]).reshape(key[0])
        times = np.frombuffer(key[3]).reshape(key[1])
        chunked = chunk is not None and chunk < B
        for b in sorted(drv._step_batch_sizes(B, multiple, chunk,
                                              pad_chunks=pad_chunks)):
            # the staging dtype is the precision policy's transfer dtype
            # (driver.stage_dtype): f64 host staging by default, bf16
            # under precision="bf16_io" — it is part of the step key,
            # so warmup must plan exactly what run_pipeline will stage
            plans.append((freqs, times, (b, nf, nt),
                          drv.stage_dtype(config.precision), chunked))
    return plans


# ---------------------------------------------------------------------------
# cache hygiene: size cap + LRU eviction
# ---------------------------------------------------------------------------


def _touch(path: str) -> None:
    """Refresh a cache file's mtime (LRU recency for the cap walk)."""
    try:
        os.utime(path, None)
    except OSError:
        pass


def cache_cap_bytes() -> int | None:
    """The configured cache size cap in bytes, or None when disabled
    (``SCINT_COMPILE_CACHE_MAX_MB=0``/``off``)."""
    val = os.environ.get(CAP_ENV)
    if val is not None and val.strip().lower() in _DISABLED_VALUES:
        return None
    try:
        mb = int(val) if val is not None else DEFAULT_CAP_MB
    except ValueError:
        raise ValueError(f"{CAP_ENV} must be an integer MB count, "
                         f"got {val!r}")
    return None if mb <= 0 else mb * (1 << 20)


def enforce_cache_cap(path: str | None = None,
                      cap_bytes: int | None = None) -> int:
    """Evict least-recently-used cache files until the directory's
    total size fits the cap (``SCINT_COMPILE_CACHE_MAX_MB``, default
    4096 MB; 0/off disables).  Returns the number of files evicted and
    increments the ``compile_cache_evictions`` counter.

    LRU order is file mtime.  The ``.jaxexec``/``.jaxexport`` artifacts
    — the layer warm consumers actually serve from — are touched by
    ``load_step`` on every hit, so hot signatures stay young; XLA's own
    persistent-cache entries are only written on a compile MISS (jax
    does not touch them on a hit), so for those the order degrades to
    FIFO — an unpacked warm-cache artifact starts young
    (``unpack_warm_cache`` refreshes mtimes) but a long-lived pod that
    churns many one-off programs can age the catalog's XLA entries out,
    which costs the JIT-FALLBACK path a recompile (the executable
    artifacts still serve).  The artifact manifest (MANIFEST.json) is
    exempt — provenance must outlive eviction."""
    d = path if path is not None else cache_dir()
    cap = cap_bytes if cap_bytes is not None else cache_cap_bytes()
    if d is None or cap is None or not os.path.isdir(d):
        return 0
    entries = []
    total = 0
    for root, _dirs, files in os.walk(d):
        for name in files:
            if name == MANIFEST_NAME:
                continue
            p = os.path.join(root, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, p))
            total += st.st_size
    if total <= cap:
        return 0
    evicted = 0
    for _mtime, size, p in sorted(entries):
        if total <= cap:
            break
        try:
            os.remove(p)
        except OSError:
            continue
        _LOADED.pop(p, None)   # a memoized step must not outlive its file
        total -= size
        evicted += 1
    if evicted:
        obs.inc("compile_cache_evictions", evicted)
    return evicted


# ---------------------------------------------------------------------------
# warm-cache artifact: pack / verify / unpack
# ---------------------------------------------------------------------------


def _env_fingerprint() -> dict:
    """The runtime identity a warm cache is only valid for: XLA's
    serialized executables are keyed (by jax itself and by our step
    keys) on the jax/jaxlib versions and backend platform, and the AOT
    artifacts additionally on this package's source tree."""
    import jax
    import jaxlib

    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
            "backend": jax.default_backend(),
            "source_fp": _source_fingerprint()}


def manifest_path(cache: str | None = None) -> str | None:
    d = cache if cache is not None else cache_dir()
    return None if d is None else os.path.join(d, MANIFEST_NAME)


def artifact_manifest(cache: str | None = None) -> dict | None:
    """The manifest of the warm-cache artifact this cache dir was
    packed from / unpacked to, or None when the cache was never
    associated with one."""
    p = manifest_path(cache)
    if p is None or not os.path.exists(p):
        return None
    try:
        with open(p) as fh:
            man = json.load(fh)
        return man if isinstance(man, dict) else None
    except (OSError, ValueError):
        return None


def pack_warm_cache(out_path: str, cache: str | None = None,
                    catalog_digest: str | None = None,
                    extra: dict | None = None) -> dict:
    """Pack the (already warmed) persistent cache into a relocatable
    gzip tarball — the BUILD-ARTIFACT half of the cold-start fix: CI
    runs ``warmup --catalog`` once, publishes this file, and every
    fresh pod starts warm by unpacking it (seconds) instead of
    compiling the catalog (minutes).

    Writes ``MANIFEST.json`` (format, jax/jaxlib/backend versions,
    package source fingerprint, catalog digest, file count/bytes) into
    the cache dir first, so the tarball is self-describing and
    :func:`unpack_warm_cache` can refuse a version-skewed artifact.
    Returns the manifest."""
    import tarfile
    import time as _time

    d = cache if cache is not None else cache_dir()
    if d is None or not os.path.isdir(d):
        raise ValueError("pack_warm_cache: no cache directory to pack "
                         f"({ENV_VAR} disabled or {d!r} missing); run "
                         "`scintools-tpu warmup --catalog` first")
    files = []
    total = 0
    for root, _dirs, names in os.walk(d):
        for name in names:
            if name == MANIFEST_NAME or ".tmp" in name:
                continue
            p = os.path.join(root, name)
            try:
                total += os.path.getsize(p)
            except OSError:
                continue
            files.append(os.path.relpath(p, d))
    if not files:
        raise ValueError(f"pack_warm_cache: {d} holds no cache entries; "
                         "run `scintools-tpu warmup --catalog` first")
    man = dict(_env_fingerprint(), format=_FORMAT,
               files=len(files), bytes=total,
               created_at=round(_time.time(), 1))
    if catalog_digest:
        man["digest"] = str(catalog_digest)
    if extra:
        man.update(extra)
    mp = manifest_path(d)
    tmp = f"{mp}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(man, fh, indent=1)
    os.replace(tmp, mp)
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    out_tmp = f"{out_path}.tmp.{os.getpid()}"
    with tarfile.open(out_tmp, "w:gz") as tar:
        tar.add(mp, arcname=MANIFEST_NAME)
        for rel in sorted(files):
            tar.add(os.path.join(d, rel), arcname=rel)
    os.replace(out_tmp, out_path)
    obs.inc("cache_artifact_packed")
    return man


def verify_artifact(manifest: dict) -> list:
    """Mismatches between an artifact manifest and THIS runtime — empty
    when the artifact is directly usable.  Each entry is a human-
    readable ``"field: artifact=X runtime=Y"`` string."""
    fp = _env_fingerprint()
    out = []
    if manifest.get("format") != _FORMAT:
        out.append(f"format: artifact={manifest.get('format')} "
                   f"runtime={_FORMAT}")
    for k, v in fp.items():
        have = manifest.get(k)
        if have != v:
            out.append(f"{k}: artifact={have} runtime={v}")
    return out


def unpack_warm_cache(tar_path: str, cache: str | None = None,
                      force: bool = False) -> dict:
    """Unpack a warm-cache artifact into the persistent cache dir (the
    fresh-pod cold-start path: unpack, then serve/process — the first
    step deserializes instead of compiling).

    The embedded manifest is verified against THIS runtime's
    jax/jaxlib/backend versions and package source fingerprint before
    any file is extracted; a mismatch raises ``ValueError`` (counted as
    ``cache_artifact_rejected``) unless ``force=True`` — a skewed cache
    is not dangerous (keys miss and the program recompiles) but it is a
    silent return to minutes-long cold starts, which must be loud.
    Member paths are validated (no absolute paths, no ``..``).
    Returns the manifest."""
    import tarfile

    d = cache if cache is not None else cache_dir()
    if d is None:
        raise ValueError(f"unpack_warm_cache: {ENV_VAR} is disabled; "
                         "nowhere to unpack")
    with tarfile.open(tar_path, "r:gz") as tar:
        try:
            fh = tar.extractfile(MANIFEST_NAME)
            if fh is None:  # a non-file member under the manifest name
                raise ValueError("manifest member is not a file")
            man = json.load(fh)
        except (KeyError, ValueError, TypeError):
            raise ValueError(f"{tar_path}: not a warm-cache artifact "
                             f"(no readable {MANIFEST_NAME})")
        mismatches = verify_artifact(man)
        if mismatches and not force:
            obs.inc("cache_artifact_rejected")
            raise ValueError(
                f"{tar_path}: artifact does not match this runtime "
                f"({'; '.join(mismatches)}); rebuild it with "
                "scripts/build_warm_cache.py, or pass force=True to "
                "unpack anyway (stale keys simply miss and recompile)")
        os.makedirs(d, exist_ok=True)
        for member in tar.getmembers():
            name = member.name
            if (name != os.path.normpath(name) or name.startswith(("/", ".."))
                    or os.path.isabs(name)
                    or not (member.isfile() or member.isdir())):
                raise ValueError(f"{tar_path}: unsafe member {name!r}")
        tar.extractall(d)
        members = [m.name for m in tar.getmembers() if m.isfile()]
    obs.inc("cache_artifact_unpacked")
    # the unpacked entries carry the pack-time mtimes; refresh ONLY the
    # extracted members so a capped cache does not immediately evict
    # the artifact it was just seeded with — pre-existing entries keep
    # their true (older) recency, or the next eviction pass would pick
    # victims in arbitrary order and could delete the fresh seed
    for name in members:
        _touch(os.path.join(d, name))
    enforce_cache_cap(d)
    return man
