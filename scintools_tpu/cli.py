"""Command-line interface.

The reference ships an argparse stub with zero arguments that does
nothing (scintools/scintools.py:1-16).  This is the real CLI planned in
SURVEY.md §5: ``info`` / ``process`` / ``sort`` / ``sim`` /
``curvature`` / ``wavefield`` / ``bench``.

    python -m scintools_tpu process obs1.dynspec obs2.dynspec \
        --lamsteps --backend jax --results results.csv --store runs/survey

``process`` is resumable: with ``--store`` each finished epoch is written
to a content-hash-keyed store, and a rerun skips everything already done.
"""

from __future__ import annotations

import argparse
import glob
import json
import sys

from . import obs


def _xprof_ctx(dirpath: str | None):
    """``--xprof DIR`` bracket (ISSUE 12): wrap the run's measure
    window in ``jax.profiler.trace`` so a TensorBoard/XProf-loadable
    device timeline lands in DIR — with the pipeline's
    ``TraceAnnotation`` regions (pipeline.stage/step/gather,
    serve.batch) naming what the device was doing.  nullcontext when
    the flag is unset."""
    from .utils.timing import xprof_bracket

    return xprof_bracket(dirpath)


def _expand(patterns: list[str]) -> list[str]:
    from .utils import remove_duplicates

    out = []
    for p in patterns:
        hits = sorted(glob.glob(p))
        out.extend(hits if hits else [p])
    return remove_duplicates(out)


def cmd_info(args) -> int:
    from .pipeline import Dynspec

    rc = 0
    for fn in _expand(args.files):
        try:
            print(Dynspec(filename=fn, process=False).info())
        except Exception as e:
            print(f"{fn}: unreadable ({e!r})", file=sys.stderr)
            rc = 1
    return rc


def _synth_spec_dict_from_args(args) -> dict | None:
    """The --synthetic flag set as a canonical sparse SynthSpec dict
    (sim.campaign.spec_to_dict form) — the serve job payload, the
    resume-key ingredient and the run_pipeline(synthetic=) input, built
    ONCE so process/warmup/submit agree on the campaign identity.
    Returns None when --synthetic was not given."""
    n = getattr(args, "synthetic", None)
    if n is None:
        return None
    from .sim import campaign

    kind = getattr(args, "synth_kind", "screen")
    d: dict = {"kind": kind, "n_epochs": int(n)}
    if getattr(args, "synth_seed", 0):
        d["seed"] = int(args.synth_seed)
    for flag, field in (("synth_dt", "dt"), ("synth_freq", "freq")):
        val = getattr(args, flag, None)
        if val is not None:
            d[field] = float(val)
    if kind == "screen":
        params = {}
        if getattr(args, "synth_nf", None) is not None:
            params["nf"] = int(args.synth_nf)
        if getattr(args, "synth_nt", None) is not None:
            # the screen's scan axis IS the time axis (nx samples)
            params["nx"] = int(args.synth_nt)
            params["ny"] = int(args.synth_nt)
        if getattr(args, "synth_mb2", None) is not None:
            params["mb2"] = float(args.synth_mb2)
        if getattr(args, "synth_dlam", None) is not None:
            params["dlam"] = float(args.synth_dlam)
        if getattr(args, "synth_pac", False):
            params["pac"] = True
        if params:
            d["params"] = params
        if getattr(args, "synth_df", None) is not None:
            raise SystemExit("--synth-df applies to the arc/acf grid "
                             "kinds; the screen kind derives its "
                             "frequency axis from --synth-dlam")
    else:
        for flag, field in (("synth_nf", "nf"), ("synth_nt", "nt")):
            val = getattr(args, flag, None)
            if val is not None:
                d[field] = int(val)
        if getattr(args, "synth_df", None) is not None:
            d["df"] = float(args.synth_df)
        if kind == "acf":
            if getattr(args, "synth_tau", None) is not None:
                d["tau_s"] = float(args.synth_tau)
            if getattr(args, "synth_dnu", None) is not None:
                d["dnu_mhz"] = float(args.synth_dnu)
        if getattr(args, "synth_pac", False) \
                or getattr(args, "synth_mb2", None) is not None \
                or getattr(args, "synth_dlam", None) is not None:
            raise SystemExit("--synth-mb2/--synth-dlam/--synth-pac "
                             "apply to the screen kind only")
    if kind != "acf" and (getattr(args, "synth_tau", None) is not None
                          or getattr(args, "synth_dnu", None) is not None):
        raise SystemExit("--synth-tau/--synth-dnu inject the acf "
                         "kind's ground truth; use --synth-kind acf")
    try:
        # canonicalise through the spec class: validation + the sparse
        # form sparse/materialised submitters share
        return campaign.spec_to_dict(campaign.spec_from_dict(d))
    except (TypeError, ValueError) as e:
        raise SystemExit(str(e))


def _infer_spec_dict_from_args(args) -> dict | None:
    """The --infer flag set as a canonical sparse InferSpec dict
    (infer.infer_to_dict form) — the serve job payload and the direct
    engine's resume-key ingredient, built ONCE so process/submit agree
    on the optimiser identity.  Returns None when --infer was not
    given; rejects orphan --infer-* knobs (they would silently do
    nothing)."""
    flags = (("infer_steps", "opt_steps", int),
             ("infer_starts", "starts", int),
             ("infer_lr", "lr", float),
             ("infer_tol", "tol", float),
             ("infer_spread", "spread", float),
             ("infer_seed", "seed", int))
    if not getattr(args, "infer", False):
        orphans = [f"--{flag.replace('_', '-')}"
                   for flag, _f, _c in flags
                   if getattr(args, flag, None) is not None]
        if orphans:
            raise SystemExit(f"{', '.join(orphans)} tune the gradient "
                             "fit; add --infer")
        return None
    from .infer import infer_from_dict, infer_to_dict

    d: dict = {}
    for flag, field, cast in flags:
        val = getattr(args, flag, None)
        if val is not None:
            d[field] = cast(val)
    try:
        # canonicalise through the spec class: validation + the sparse
        # form sparse/materialised submitters share
        return infer_to_dict(infer_from_dict(d))
    except (TypeError, ValueError) as e:
        raise SystemExit(str(e))


def _search_spec_dict_from_args(args) -> dict | None:
    """The --search flag set as a canonical sparse SearchSpec dict
    (search.search_to_dict form) — the serve job payload and the
    direct engine's resume-key ingredient, built ONCE so
    process/submit/warmup agree on the bank identity.  Returns None
    when --search was not given; rejects orphan --search-* knobs
    (they would silently do nothing)."""
    flags = (("search_trials", "n_trials", int),
             ("search_eta_min", "eta_min", float),
             ("search_eta_max", "eta_max", float),
             ("search_width", "width", float),
             ("search_rows", "delay_rows", int),
             ("search_min_row", "min_row", int),
             ("search_top_k", "top_k", int),
             ("search_decim", "decim", int))
    if not getattr(args, "search", False):
        orphans = [f"--{flag.replace('_', '-')}"
                   for flag, _f, _c in flags
                   if getattr(args, flag, None) is not None]
        if orphans:
            raise SystemExit(f"{', '.join(orphans)} shape the "
                             "template bank; add --search")
        return None
    from .search import search_from_dict, search_to_dict

    d: dict = {}
    for flag, field, cast in flags:
        val = getattr(args, flag, None)
        if val is not None:
            d[field] = cast(val)
    try:
        # canonicalise through the spec class: validation + the sparse
        # form sparse/materialised submitters share
        return search_to_dict(search_from_dict(d))
    except (TypeError, ValueError) as e:
        raise SystemExit(str(e))


def _validate_estimator_flags(args) -> None:
    """Shared --arc-bracket/--arc-method/--pad-chunks fail-fast for
    process, warmup and submit: a warmup or submit must reject exactly
    the configs the survey would reject, from one rule site."""
    bracket = getattr(args, "arc_bracket", None)
    if bracket is not None and not (0 < bracket[0] < bracket[1]):
        raise SystemExit(f"--arc-bracket must be 0 < LO < HI, got "
                         f"{bracket[0]} {bracket[1]}")
    if (getattr(args, "arc_method", "norm_sspec") == "thetatheta"
            and not args.no_arc and bracket is None):
        raise SystemExit("--arc-method thetatheta requires "
                         "--arc-bracket LO HI (the curvature sweep "
                         "range)")
    if (getattr(args, "pad_chunks", False)
            and getattr(args, "chunk_epochs", None) is None):
        raise SystemExit("--pad-chunks pads the final chunk up to "
                         "--chunk-epochs; set --chunk-epochs")
    synth = _synth_spec_dict_from_args(args)
    if synth is not None and getattr(args, "clean", False):
        raise SystemExit("--clean repairs loaded epochs; a synthetic "
                         "campaign has nothing to clean (and the knob "
                         "would fork the job identity for nothing)")
    from .serve.queue import validate_job_cfg
    try:
        # the FULL option dict (not a subset): validate_job_cfg builds
        # the worker's own PipelineConfig from it and runs the one rule
        # site (PipelineConfig.validate), so flags that interact —
        # --arc-bracket satisfying thetatheta's finite-window rule,
        # --split-programs vs --arc-method — are judged together
        cfg = _estimator_opts(args)
        if synth is not None:
            cfg = dict(cfg, synthetic=synth)
        infer_d = _infer_spec_dict_from_args(args)
        if infer_d is not None:
            if synth is None:
                raise SystemExit("--infer fits a --synthetic "
                                 "campaign's physics by gradient "
                                 "descent; add --synthetic N")
            # rides beside the campaign payload: validate_job_cfg runs
            # the one infer rule site (validate_infer_config), so a
            # process/submit --infer rejects exactly what the worker
            # would reject
            cfg = dict(cfg, infer=infer_d)
        search_d = _search_spec_dict_from_args(args)
        if search_d is not None:
            if synth is None:
                raise SystemExit("--search scores a --synthetic "
                                 "campaign's epochs against the "
                                 "template bank; add --synthetic N")
            # rides beside the campaign payload: validate_job_cfg runs
            # the one search rule site (validate_search_config,
            # including the infer/search mutual exclusion), so a
            # process/submit --search rejects exactly what the worker
            # would reject
            cfg = dict(cfg, search=search_d)
        validate_job_cfg(cfg)
    except ValueError as e:
        raise SystemExit(str(e))


def cmd_process(args) -> int:
    from .pipeline import Dynspec
    from .io.results import results_row, write_results
    from .utils import (ResultsStore, StageTimers, content_key, get_logger,
                        log_event)

    log = get_logger()
    timers = StageTimers()
    files = _expand(args.files)
    store = ResultsStore(args.store) if args.store else None
    if args.batched and args.backend != "jax":
        # the batched engine IS the jax pipeline; record that truthfully
        # in the resume key rather than diverging silently
        log_event(log, "note",
                  msg="--batched runs the jax device pipeline; "
                      "backend set to jax")
        args.backend = "jax"
    cfg = ("process", args.lamsteps, args.backend, not args.no_arc,
           not args.no_scint)
    if getattr(args, "clean", False):
        # cleaned results are different results: new resume key
        cfg += ("clean",)
    # non-default estimator settings enter the resume key (different
    # estimators are different results); defaults keep the legacy key so
    # existing stores still resume
    arc_method = getattr(args, "arc_method", "norm_sspec")
    arc_bracket = getattr(args, "arc_bracket", None)
    scint_2d = getattr(args, "scint_2d", False)
    mcmc = getattr(args, "mcmc", False)
    if scint_2d:
        cfg += ("scint2d",)
    # fail fast on estimator misconfiguration, before any file I/O
    _validate_estimator_flags(args)
    if arc_method != "norm_sspec" or arc_bracket is not None:
        cfg += (arc_method, tuple(arc_bracket or ()))
    # precision / fft-length policies change results (bf16 rounding,
    # composite-grid sampling): non-defaults enter the resume key
    for knob, dflt in (("precision", "f32"), ("fft_lens", "pow2")):
        val = getattr(args, knob, dflt)
        if val != dflt:
            cfg += (f"{knob}={val}",)
    if getattr(args, "sspec_crop", False):
        cfg += ("sspec_crop",)
    if getattr(args, "fused_sspec", False):
        # fused kernels are within the fit budget but not bit-identical:
        # different results, different resume key
        cfg += ("fused_sspec",)
    if mcmc:
        if args.batched:
            raise SystemExit("--mcmc samples per-epoch posteriors in "
                             "the per-file engine; drop --batched "
                             "(batched surveys use the deterministic "
                             "fits)")
        if args.no_scint and not scint_2d:
            raise SystemExit("--mcmc has nothing to sample with "
                             "--no-scint (add --scint-2d or drop "
                             "--no-scint)")
        cfg += ("mcmc",)   # posterior rows must not resume as LM rows
    # prerequisite checks stay ahead of the plots mkdir and the store
    # resume scan (which hashes every input file): truly fail-fast
    if not args.batched:
        for flag, name in ((getattr(args, "mesh", None), "--mesh"),
                           (getattr(args, "chunk_epochs", None),
                            "--chunk-epochs"),
                           (getattr(args, "xprof", None), "--xprof")):
            if flag is not None:
                raise SystemExit(f"{name} only applies to the batched "
                                 "engine; add --batched")
        for flag, name in ((getattr(args, "pad_chunks", False),
                            "--pad-chunks"),
                           (getattr(args, "no_async", False),
                            "--no-async"),
                           (getattr(args, "bucket", False),
                            "--bucket"),
                           (getattr(args, "precision", "f32") != "f32",
                            "--precision"),
                           (getattr(args, "fft_lens", "pow2") != "pow2",
                            "--fft-lens"),
                           (getattr(args, "sspec_crop", False),
                            "--sspec-crop"),
                           (getattr(args, "fused_sspec", False),
                            "--fused-sspec"),
                           (getattr(args, "split_programs", False),
                            "--split-programs")):
            if flag:
                raise SystemExit(f"{name} only applies to the batched "
                                 "engine; add --batched")
        if getattr(args, "arc_stack", False):
            raise SystemExit("--arc-stack stacks profiles across the "
                             "batch; add --batched")
    if args.batched and getattr(args, "arc_stack", False):
        # fail as a usage error, not a quarantined whole-survey
        # pipeline failure inside run_pipeline
        if args.no_arc:
            raise SystemExit("--arc-stack needs the arc fit; drop "
                             "--no-arc")
        if arc_method != "norm_sspec":
            raise SystemExit("--arc-stack requires "
                             "--arc-method norm_sspec (the campaign "
                             "stack averages normalised profiles)")
    if getattr(args, "full_csv", False) and not (args.store
                                                 and args.results):
        raise SystemExit("--full-csv exports the store's columns: it "
                         "needs both --store and --results")
    synth_d = _synth_spec_dict_from_args(args)
    if synth_d is not None:
        if not args.batched:
            raise SystemExit("--synthetic generates and analyses "
                             "on-device through the batched engine; "
                             "add --batched")
        if files:
            raise SystemExit("--synthetic campaigns take no input "
                             "files (the campaign generates its own "
                             "epochs on-device)")
        if args.plots:
            raise SystemExit("--batched does not render per-epoch "
                             "plots; drop --plots")
        infer_d = _infer_spec_dict_from_args(args)
        if infer_d is not None:
            return _process_infer(args, synth_d, infer_d, cfg, store,
                                  log, timers)
        search_d = _search_spec_dict_from_args(args)
        if search_d is not None:
            return _process_search(args, synth_d, search_d, cfg, store,
                                   log, timers)
        return _process_synthetic(args, synth_d, cfg, store, log,
                                  timers)
    if not files:
        raise SystemExit("no input files (pass psrflux files, or "
                         "--synthetic N for an on-device campaign)")
    if args.plots:
        import os

        os.makedirs(args.plots, exist_ok=True)
    if store is not None:
        todo = store.pending(files, lambda f: content_key(f, cfg))
        log_event(log, "resume", total=len(files), todo=len(todo),
                  done=len(files) - len(todo))
        files = todo
    if args.batched:
        if args.plots:
            raise SystemExit("--batched does not render per-epoch plots; "
                             "drop --plots or run without --batched")
        return _process_batched(args, files, cfg, store, log, timers)
    failed = 0
    for fn in files:
        try:
            with timers.stage("load+process"):
                if getattr(args, "clean", False):
                    # RFI/gain cleaning between load and the fits:
                    # channel triage -> repair -> bandpass removal (the
                    # reference delegates this class to coast_guard,
                    # scint_utils.py:19-56); fits recompute lazily
                    ds = Dynspec(filename=fn, process=False,
                                 lamsteps=args.lamsteps,
                                 backend=args.backend)
                    ds.trim_edges().refill() \
                      .zap(method="channels", sigma=5) \
                      .zap(method="subints", sigma=5).refill() \
                      .correct_band()
                else:
                    ds = Dynspec(filename=fn, process=True,
                                 lamsteps=args.lamsteps,
                                 backend=args.backend)
            scint = arc = None
            tilt_row = {}
            if not args.no_scint:
                with timers.stage("scint_fit"):
                    scint = ds.get_scint_params(mcmc=mcmc)
            if scint_2d:
                with timers.stage("scint_fit_2d"):
                    import math

                    ds.get_scint_params(method="acf2d", mcmc=mcmc)
                    if not math.isfinite(float(ds.tilt)):
                        # quarantine like any failed fit (retried on
                        # resume), not stored as a NaN result
                        raise ValueError(
                            "2-D ACF fit returned non-finite tilt")
                    tilt_row = dict(tilt=float(ds.tilt),
                                    tilterr=float(ds.tilterr))
            if not args.no_arc:
                with timers.stage("arc_fit"):
                    fkw = {"method": arc_method}
                    if arc_bracket is not None:
                        if arc_method == "thetatheta":
                            fkw["etamin"], fkw["etamax"] = arc_bracket
                        else:
                            fkw["constraint"] = tuple(arc_bracket)
                    if arc_method == "thetatheta":
                        # Dynspec.fit_arc's numsteps default (10000) sizes
                        # the power-profile grid; the concentration sweep
                        # needs ~128 (same cap the batched driver applies)
                        fkw["numsteps"] = 128
                    arc = ds.fit_arc(lamsteps=args.lamsteps, **fkw)
            row = results_row(ds.data, scint=scint, arc=arc)
            row.update(tilt_row)   # store rows only; CSV keeps the
            #                        reference schema (as eta_left does)
            if args.plots:
                with timers.stage("plots"):
                    import matplotlib

                    matplotlib.use("Agg")
                    ds.plot_all(filename=f"{args.plots}/"
                                f"{row['name']}_all.png")
                    if mcmc and getattr(ds, "mcmc_chain", None) is not None:
                        # the reference's corner export
                        # (dynspec.py:1025-1031)
                        from .plotting import plot_posterior

                        labels = ["tau", "dnu", "amp", "wn"]
                        if scint_2d:   # last sampled method was acf2d
                            labels.append("tilt")
                        plot_posterior(ds.mcmc_chain, labels=labels,
                                       filename=f"{args.plots}/"
                                       f"{row['name']}_corner.png")
            # store.put last: an epoch only counts as done once all its
            # artefacts (CSV row comes from the store on export) exist
            if args.results:
                write_results(args.results, row)
            if store is not None:
                store.put(content_key(fn, cfg), row)
            log_event(log, "epoch", file=fn,
                      tau=row.get("tau"), dnu=row.get("dnu"),
                      eta=row.get("betaeta", row.get("eta")))
        except Exception as e:  # quarantine; keep the batch going
            failed += 1
            obs.inc("epochs_failed")
            log_event(log, "epoch_failed", file=fn, error=repr(e))
    if store is not None and args.results:
        store.export_csv(args.results,
                         full=getattr(args, "full_csv", False))
    print(timers.report(), file=sys.stderr)
    log_event(log, "done", processed=len(files) - failed, failed=failed)
    return 0 if failed == 0 else 1


def _load_clean_epochs(args, files, log, timers=None):
    """Shared load+clean stage of the batched engine and ``warmup``:
    trim/refill (plus the --clean chain) host-side, quarantining
    unreadable/degenerate files.  Returns (epochs, names, failed,
    quarantined) — ``quarantined`` counts the preflight rejections
    (scintools_tpu.health: structurally-bad epochs routed out with
    machine-readable reason codes before they can NaN-poison a batch
    lane; a subset of ``failed``, surfaced in the `done` summary).

    The single-epoch chain itself is ``serve.load_epoch`` — ONE
    implementation, so a served epoch enters the pipeline bit-identical
    to a direct run (the byte-equality contract of docs/serving.md)."""
    import contextlib

    from .health import PreflightError
    from .serve import load_epoch
    from .utils import log_event

    epochs, names, failed, quarantined = [], [], 0, 0
    stage = (timers.stage("load+clean") if timers is not None
             else contextlib.nullcontext())
    with stage:
        for fn in files:
            try:
                epochs.append(load_epoch(
                    fn, clean=getattr(args, "clean", False)))
                names.append(fn)
            except PreflightError as e:
                # counters + epoch_quarantined event already emitted at
                # the raise site (health.quarantine_check)
                failed += 1
                quarantined += 1
                obs.inc("epochs_failed")
                log_event(log, "epoch_failed", file=fn, error=repr(e))
            except Exception as e:
                failed += 1
                obs.inc("epochs_failed")
                log_event(log, "epoch_failed", file=fn, error=repr(e))
    return epochs, names, failed, quarantined


def _estimator_opts(args) -> dict:
    """The shared estimator flags as a plain option dict — the job
    payload of the serve protocol AND the input of the one
    PipelineConfig builder (serve.config_from_opts), so ``process
    --batched``, ``warmup`` and a served survey all run the identical
    config."""
    opts = dict(lamsteps=bool(args.lamsteps),
                no_arc=bool(getattr(args, "no_arc", False)),
                no_scint=bool(getattr(args, "no_scint", False)),
                scint_2d=bool(getattr(args, "scint_2d", False)),
                arc_asymm=bool(getattr(args, "arc_asymm", False)),
                arc_method=getattr(args, "arc_method", "norm_sspec"),
                arc_stack=bool(getattr(args, "arc_stack", False)))
    bracket = getattr(args, "arc_bracket", None)
    if bracket is not None:
        opts["arc_bracket"] = [float(bracket[0]), float(bracket[1])]
    if getattr(args, "clean", False):
        opts["clean"] = True
    # performance-policy knobs enter the option dict (and therefore the
    # job identity / resume key / batch bucket) only when non-default,
    # so legacy stores and queued jobs keep their identities
    if getattr(args, "precision", "f32") != "f32":
        opts["precision"] = str(args.precision)
    if getattr(args, "fft_lens", "pow2") != "pow2":
        opts["fft_lens"] = str(args.fft_lens)
    if getattr(args, "sspec_crop", False):
        opts["sspec_crop"] = True
    if getattr(args, "fused_sspec", False):
        opts["fused_sspec"] = True
    if getattr(args, "split_programs", False):
        # placement knob: rides in the option dict so warmup/serve
        # build the same PipelineConfig, but cfg_signature strips it
        # from the job identity and cmd_process keeps it out of the
        # resume key (results are bit-identical either way)
        opts["split_programs"] = True
    for k in ("arc_numsteps", "lm_steps"):
        if getattr(args, k, None) is not None:
            opts[k] = int(getattr(args, k))
    return opts


def _pipeline_config_from_args(args):
    """PipelineConfig from the shared process/warmup estimator flags —
    one builder, so a warmup compiles exactly the config the survey
    will run."""
    from .serve import config_from_opts

    return config_from_opts(_estimator_opts(args))


def _process_batched(args, files, cfg, store, log, timers) -> int:
    """Batched engine for cmd_process: trim/refill host-side, then ONE
    jit-compiled step per shape bucket over the device mesh
    (parallel.run_pipeline) instead of a per-file Python loop."""
    import os

    import numpy as np

    from .io.results import (batch_lane_row, results_row, row_fit_values,
                             write_results)
    from .parallel import make_mesh, run_pipeline, survey_routes
    from .utils import content_key, log_event

    epochs, names, failed, quarantined = _load_clean_epochs(
        args, files, log, timers=timers)
    processed = 0
    if epochs:
        pcfg = _pipeline_config_from_args(args)
        mesh_shape = getattr(args, "mesh", None)
        try:
            # inside the quarantine handler: an invalid --mesh for this
            # host's device count must fail like any pipeline failure
            # (logged, rc=1), not as a raw traceback
            mesh = (make_mesh(tuple(int(x) for x in mesh_shape))
                    if mesh_shape else make_mesh())
            # the resolved auto routes: matmul vs fft cuts differ at f32
            # rounding, so a survey resumed on a different host class
            # drifts numerically — make that diagnosable
            routes = survey_routes(epochs, pcfg, mesh=mesh,
                                   chunk=getattr(args, "chunk_epochs",
                                                 None),
                                   pad_chunks=getattr(args, "pad_chunks",
                                                      False))
            # routes keys like 'bucket0:5of256x512:step8' are not valid
            # identifiers — pass as one JSON field, never ** unpacking
            # (non-identifier ** keys are implementation-defined)
            log_event(log, "routes", routes=json.dumps(routes))
            with timers.stage("batched_pipeline"), \
                    _xprof_ctx(getattr(args, "xprof", None)):
                buckets = run_pipeline(
                    epochs, pcfg, mesh=mesh,
                    chunk=getattr(args, "chunk_epochs", None),
                    async_exec=not getattr(args, "no_async", False),
                    pad_chunks=getattr(args, "pad_chunks", False),
                    bucket=getattr(args, "bucket", False))
        except Exception as e:
            log_event(log, "pipeline_failed", error=repr(e),
                      epochs=len(epochs))
            failed += len(epochs)
            buckets = []
        if buckets and store is not None:
            # Baseline only updates on a run that produced results.
            # Drift means: a bucket key BOTH runs resolved (identical
            # composition must resolve identically), or a change in the
            # composition-free fields (target platform, scrunch route).
            # scint_cuts on non-shared keys is NOT drift — the auto cut
            # legitimately depends on the per-step batch shape, which
            # shrinks on every partial resume.
            prev = store.get_meta("routes") or {}
            # .get: a schema-drifted / hand-edited meta record must read
            # as "drifted", not crash the run (get_meta promises metadata
            # degrades rather than failing)
            cf = lambda r: {(v.get("target_is_tpu"),  # noqa: E731
                             v.get("arc_scrunch_rows"))
                            for v in r.values() if isinstance(v, dict)}
            if prev and (any(prev[k] != routes[k]
                             for k in set(prev) & set(routes))
                         or cf(prev) != cf(routes)):
                log_event(log, "routes_changed", previous=prev,
                          current=routes)
            # merge so a partial resume never erases the full-survey
            # baseline
            store.put_meta("routes", {**prev, **routes})
        for bucket_no, (indices, res) in enumerate(buckets):
            if res.arc_stacked is not None:
                # one campaign curvature per shape-bucket (all its
                # epochs share a grid); meta + log only — per-epoch
                # rows keep the reference CSV schema.  A chunked bucket
                # yields one SUB-campaign fit per chunk (leaves of
                # ndim>=1) — reported as lists.
                key = "betaeta" if args.lamsteps else "eta"

                def _vals(x):
                    a = np.asarray(x).ravel()
                    return float(a[0]) if a.size == 1 else [
                        float(v) for v in a]

                # files in BUCKET ORDER (not sorted): with
                # --chunk-epochs C the per-chunk sub-campaign k covers
                # files[k*C:(k+1)*C], so the record stays mappable
                camp_files = [os.path.basename(names[i]) for i in indices]
                camp = {"bucket": bucket_no, "n_epochs": len(indices),
                        "files": camp_files,
                        key: _vals(res.arc_stacked.eta),
                        key + "err": _vals(res.arc_stacked.etaerr),
                        key + "err2": _vals(res.arc_stacked.etaerr2)}
                if np.ndim(np.asarray(res.arc_stacked.eta)) >= 1:
                    # chunked bucket: one SUB-campaign fit per chunk
                    # (S/N grows as sqrt(chunk), not sqrt(n_epochs)).
                    # Record the EFFECTIVE chunk via run_pipeline's own
                    # adjustment rule (single source of truth):
                    # sub-campaign k covers files[k*C:(k+1)*C] (the
                    # final chunk's divisibility pad-lanes are NaN and
                    # contribute nothing)
                    from .parallel.driver import _adjust_chunk

                    mult = (mesh.shape["data"] if mesh is not None
                            else 1)
                    camp["chunk_epochs"] = _adjust_chunk(
                        mult, int(args.chunk_epochs))
                log_event(log, "arc_stack", bucket=bucket_no,
                          n_epochs=len(indices), **{
                              key: camp[key], key + "err": camp[key + "err"]})
                if store is not None:
                    # one atomic meta file per campaign, keyed by the
                    # FULL PATHS it covers (basenames can collide across
                    # sessions): concurrent runs can't lose each other's
                    # records (no shared-list read-modify-write),
                    # identical re-runs overwrite idempotently, and a
                    # RESUMED partial survey writes a separate record
                    # whose "files" list says exactly which sub-campaign
                    # it is.  Enumerate with store.meta_names("arc_stack.").
                    # "arc_stack:" prefix: the joined string must never
                    # itself be an existing path, or content_key would
                    # hash file BYTES for single-epoch campaigns (and
                    # byte-identical copies would collide)
                    # realpath-normalize so the same campaign invoked
                    # with different path spellings (relative vs ./ vs
                    # absolute vs symlinked) keys ONE record, as
                    # idempotence requires.  Digest-scheme change
                    # (round 5): stores written before this keyed on the
                    # raw spelling; those records remain enumerable but
                    # a re-run writes under the normalized key.
                    digest = content_key(
                        "arc_stack:" + "\n".join(
                            os.path.realpath(names[i]) for i in indices),
                        ())[:12]
                    store.put_meta(f"arc_stack.{digest}", camp)
            for lane, idx in enumerate(indices):
                row = results_row(epochs[idx])
                # one shared per-lane row builder (io.results) keeps the
                # batched CLI and the serve worker bit-identical; the
                # beyond-reference columns (etaerr2, per-arm curvatures,
                # tilt) stay store-only via write_results' schema filter
                row.update(batch_lane_row(res, lane, args.lamsteps))
                # NaN lanes are FAILED fits: quarantine (no CSV row, no
                # store entry -> retried on resume), as the per-file loop
                # does via exceptions
                fitvals = row_fit_values(row)
                if fitvals and not np.all(np.isfinite(fitvals)):
                    failed += 1
                    obs.inc("epochs_failed")
                    log_event(log, "epoch_failed", file=names[idx],
                              error="non-finite fit (NaN lane)")
                    continue
                # basename, matching the per-file loop's CSV name column
                row["name"] = os.path.basename(names[idx])
                if args.results:
                    write_results(args.results, row)
                if store is not None:
                    # buffered columnar plane: one sealed segment per
                    # bucket flush below, instead of one JSON file per
                    # row (utils/segments — ISSUE 11)
                    store.put_new_buffered(content_key(names[idx], cfg),
                                           row)
                processed += 1
                log_event(log, "epoch", file=names[idx],
                          tau=row.get("tau"),
                          eta=row.get("betaeta", row.get("eta")))
            if store is not None:
                # flush per bucket: rows are durable (and visible to a
                # concurrent reader) as each bucket completes, not at
                # campaign end
                store.flush()
    if store is not None and args.results:
        store.export_csv(args.results,
                         full=getattr(args, "full_csv", False))
    print(timers.report(), file=sys.stderr)
    log_event(log, "done", processed=processed, failed=failed,
              quarantined=quarantined)
    return 0 if failed == 0 else 1


def _process_synthetic(args, synth_d: dict, cfg, store, log,
                       timers) -> int:
    """Zero-H2D synthetic engine for cmd_process: the campaign's keys
    go to the device, the dynspec batch is generated IN the compiled
    analysis step (``run_pipeline(synthetic=...)``), and one result row
    per epoch lands in the CSV/store — same row builders and NaN-lane
    quarantine as the file-backed batched engine.

    Resumable: each epoch's store key hashes (campaign identity, epoch
    index, estimator cfg).  A fully-done campaign is skipped outright;
    a partial one re-runs the (cheap to regenerate) campaign and
    re-writes idempotent rows."""
    from .io.results import write_results
    from .parallel import make_mesh
    from .sim import campaign
    from .utils import content_key, log_event

    spec = campaign.spec_from_dict(synth_d)
    n = spec.n_epochs
    # per-epoch resume keys: one campaign digest + the epoch index,
    # in the serve route's <base>.<index> shape — store keys (and
    # therefore CSV export order) sort in epoch order, so a direct
    # campaign's CSV is byte-identical to a served `simulate` job's
    base = content_key(("synthetic", repr(synth_d)), cfg)

    def keyfn(i: int) -> str:
        return campaign.synth_row_key(base, i)

    if store is not None:
        todo = [i for i in range(n) if keyfn(i) not in store]
        log_event(log, "resume", total=n, todo=len(todo),
                  done=n - len(todo))
        if not todo:
            if args.results:
                store.export_csv(args.results,
                                 full=getattr(args, "full_csv", False))
            print(timers.report(), file=sys.stderr)
            log_event(log, "done", processed=0, failed=0, quarantined=0)
            return 0
    rows, failed = [], 0
    mesh_shape = getattr(args, "mesh", None)
    try:
        mesh = (make_mesh(tuple(int(x) for x in mesh_shape))
                if mesh_shape else make_mesh())
        with timers.stage("synthetic_pipeline"), \
                _xprof_ctx(getattr(args, "xprof", None)):
            rows = campaign.synthetic_rows(
                spec, _estimator_opts(args), mesh=mesh,
                chunk=getattr(args, "chunk_epochs", None),
                async_exec=not getattr(args, "no_async", False),
                pad_chunks=getattr(args, "pad_chunks", False),
                bucket=getattr(args, "bucket", False))
    except Exception as e:
        log_event(log, "pipeline_failed", error=repr(e), epochs=n)
        failed = n
    processed = 0
    for i, row in enumerate(rows):
        if row is None:
            # NaN lane: quarantined (no CSV row, no store entry ->
            # retried on resume), as the batched engine does
            failed += 1
            obs.inc("epochs_failed")
            log_event(log, "epoch_failed",
                      file=campaign.epoch_name(spec, i),
                      error="non-finite fit (NaN lane)")
            continue
        if args.results:
            write_results(args.results, row)
        if store is not None:
            # buffered columnar plane (auto-flushes every flush_rows,
            # so a 10^6-epoch campaign holds bounded memory and writes
            # O(flushes) segment files, not O(B) row files)
            store.put_new_buffered(keyfn(i), row)
        processed += 1
        log_event(log, "epoch", file=row["name"], tau=row.get("tau"),
                  eta=row.get("betaeta", row.get("eta")))
    if store is not None:
        store.flush()
    if store is not None and args.results:
        store.export_csv(args.results,
                         full=getattr(args, "full_csv", False))
    print(timers.report(), file=sys.stderr)
    log_event(log, "done", processed=processed, failed=failed,
              quarantined=0)
    return 0 if failed == 0 else 1


def _process_infer(args, synth_d: dict, infer_d: dict, cfg, store,
                   log, timers) -> int:
    """Gradient-inference engine for cmd_process (ISSUE 18): the
    campaign's keys go to the device and the WHOLE chain — generate ->
    differentiable loss -> vmapped multi-start Adam -> Fisher errors —
    runs as ONE compiled step (``infer.infer_rows``).  One result row
    per epoch lands in the CSV/store through the same row builder and
    NaN-lane quarantine as the served `infer` job kind, so a direct
    run's CSV is byte-identical to a served one.

    Resumable like the synthetic engine: per-epoch store keys hash
    (campaign identity, optimiser identity, epoch index, estimator
    cfg) in the serve route's ``<base>.<index>`` shape."""
    from .io.results import write_results
    from .infer import infer_rows
    from .parallel import make_mesh
    from .sim import campaign
    from .utils import content_key, log_event

    for flag, name in ((getattr(args, "chunk_epochs", None),
                        "--chunk-epochs"),
                       (getattr(args, "pad_chunks", False),
                        "--pad-chunks")):
        if flag:
            raise SystemExit(f"{name} chunks the file/simulate "
                             "engines; the infer step always runs the "
                             "campaign as one bucketed batch")
    spec = campaign.spec_from_dict(synth_d)
    n = spec.n_epochs
    # per-epoch resume keys: campaign digest + optimiser digest + the
    # epoch index — a gradient fit is a different result than a
    # summary fit of the same campaign, so the identities never alias
    base = content_key(("infer", repr(synth_d), repr(infer_d)), cfg)

    def keyfn(i: int) -> str:
        return campaign.synth_row_key(base, i)

    if store is not None:
        todo = [i for i in range(n) if keyfn(i) not in store]
        log_event(log, "resume", total=n, todo=len(todo),
                  done=n - len(todo))
        if not todo:
            if args.results:
                store.export_csv(args.results,
                                 full=getattr(args, "full_csv", False))
            print(timers.report(), file=sys.stderr)
            log_event(log, "done", processed=0, failed=0, quarantined=0)
            return 0
    obs.inc("infer_jobs")
    rows, failed = [], 0
    mesh_shape = getattr(args, "mesh", None)
    try:
        mesh = (make_mesh(tuple(int(x) for x in mesh_shape))
                if mesh_shape else make_mesh())
        with timers.stage("infer_pipeline"), \
                _xprof_ctx(getattr(args, "xprof", None)):
            rows = infer_rows(
                spec, infer_d, _estimator_opts(args), mesh=mesh,
                async_exec=not getattr(args, "no_async", False))
    except Exception as e:
        log_event(log, "pipeline_failed", error=repr(e), epochs=n)
        failed = n
    processed = 0
    for i, row in enumerate(rows):
        if row is None:
            # NaN lane: quarantined (no CSV row, no store entry ->
            # retried on resume), as the batched engine does
            failed += 1
            obs.inc("epochs_failed")
            log_event(log, "epoch_failed",
                      file=campaign.epoch_name(spec, i),
                      error="non-finite fit (NaN lane)")
            continue
        if args.results:
            write_results(args.results, row)
        if store is not None:
            store.put_new_buffered(keyfn(i), row)
        processed += 1
        log_event(log, "epoch", file=row["name"], tau=row.get("tau"),
                  eta=row.get("betaeta"),
                  converged=row.get("infer_converged"))
    if store is not None:
        store.flush()
    if store is not None and args.results:
        store.export_csv(args.results,
                         full=getattr(args, "full_csv", False))
    print(timers.report(), file=sys.stderr)
    log_event(log, "done", processed=processed, failed=failed,
              quarantined=0)
    return 0 if failed == 0 else 1


def _process_search(args, synth_d: dict, search_d: dict, cfg, store,
                    log, timers) -> int:
    """Acceleration-search engine for cmd_process (ISSUE 19): the
    campaign's keys go to the device and the WHOLE chain — generate ->
    cropped secondary spectrum -> Fourier-domain correlation against
    the resident template bank -> coarse-to-fine scores — runs as ONE
    compiled step (``search.search_rows``).  One candidate row per
    epoch lands in the CSV/store through the same row builder and
    NaN-lane quarantine as the served `search` job kind, so a direct
    run's CSV is byte-identical to a served one.

    Resumable like the synthetic engine: per-epoch store keys hash
    (campaign identity, bank identity, epoch index, estimator cfg) in
    the serve route's ``<base>.<index>`` shape."""
    from .io.results import write_results
    from .parallel import make_mesh
    from .search import search_rows
    from .sim import campaign
    from .utils import content_key, log_event

    for flag, name in ((getattr(args, "chunk_epochs", None),
                        "--chunk-epochs"),
                       (getattr(args, "pad_chunks", False),
                        "--pad-chunks")):
        if flag:
            raise SystemExit(f"{name} chunks the file/simulate "
                             "engines; the search step always runs "
                             "the campaign as one bucketed batch")
    spec = campaign.spec_from_dict(synth_d)
    n = spec.n_epochs
    # per-epoch resume keys: campaign digest + bank digest + the epoch
    # index — a matched-filter search is a different result than a
    # summary fit OR a gradient fit of the same campaign, so the
    # identities never alias
    base = content_key(("search", repr(synth_d), repr(search_d)), cfg)

    def keyfn(i: int) -> str:
        return campaign.synth_row_key(base, i)

    if store is not None:
        todo = [i for i in range(n) if keyfn(i) not in store]
        log_event(log, "resume", total=n, todo=len(todo),
                  done=n - len(todo))
        if not todo:
            if args.results:
                store.export_csv(args.results,
                                 full=getattr(args, "full_csv", False))
            print(timers.report(), file=sys.stderr)
            log_event(log, "done", processed=0, failed=0, quarantined=0)
            return 0
    obs.inc("search_jobs")
    rows, failed = [], 0
    mesh_shape = getattr(args, "mesh", None)
    try:
        mesh = (make_mesh(tuple(int(x) for x in mesh_shape))
                if mesh_shape else make_mesh())
        with timers.stage("search_pipeline"), \
                _xprof_ctx(getattr(args, "xprof", None)):
            rows = search_rows(
                spec, search_d, _estimator_opts(args), mesh=mesh,
                async_exec=not getattr(args, "no_async", False))
    except Exception as e:
        log_event(log, "pipeline_failed", error=repr(e), epochs=n)
        failed = n
    processed = 0
    for i, row in enumerate(rows):
        if row is None:
            # NaN lane: quarantined (no CSV row, no store entry ->
            # retried on resume), as the batched engine does
            failed += 1
            obs.inc("epochs_failed")
            log_event(log, "epoch_failed",
                      file=campaign.epoch_name(spec, i),
                      error="non-finite score (NaN lane)")
            continue
        if args.results:
            write_results(args.results, row)
        if store is not None:
            store.put_new_buffered(keyfn(i), row)
        processed += 1
        log_event(log, "epoch", file=row["name"], eta=row.get("eta"),
                  snr=row.get("search_snr"))
    if store is not None:
        store.flush()
    if store is not None and args.results:
        store.export_csv(args.results,
                         full=getattr(args, "full_csv", False))
    print(timers.report(), file=sys.stderr)
    log_event(log, "done", processed=processed, failed=failed,
              quarantined=0)
    return 0 if failed == 0 else 1


def cmd_warmup(args) -> int:
    """Pre-compile the batched pipeline's step set for a template +
    config, so a later ``process --batched`` run pays ZERO trace/compile
    time (the fixed-cost amortization layer, scintools_tpu.compile_cache).

    For every step signature the matching ``process --batched`` survey
    would execute — including the uneven trailing-chunk signature from
    the chunk math, unless --pad-chunks collapses it — this (a) AOT-
    exports the jit'd step as a serialized jax.export artifact keyed on
    (template axes, config, mesh, batch shape, dtype, jax/backend
    version) and (b) compiles the deserialized module with the
    persistent XLA cache enabled, so the survey process deserializes
    the step AND hits the XLA disk cache instead of re-tracing and
    re-compiling.  ``--batch`` sizes the planned survey batch (default:
    the number of template files per bucket).  A signature whose
    artifact already exists reports ``cached`` but is still compiled
    against the persistent XLA cache — near-free when warm, and it
    repairs an evicted cache entry (the AOT artifacts have no eviction;
    the XLA cache does).

    Prints one JSON line: cache dir + per-signature status/compile time.
    """
    import os
    import time

    from . import compile_cache
    from .parallel import make_mesh, make_pipeline
    from .parallel import mesh as mesh_mod
    from .parallel.driver import _resolve_chan_sharded, _resolve_donate
    from .utils import get_logger, log_event

    log = get_logger()
    files = _expand(args.files)
    _validate_estimator_flags(args)
    cache = compile_cache.enable_persistent_cache()
    if cache is None:
        print(json.dumps({"error": "compile cache disabled "
                          "(SCINT_COMPILE_CACHE=off); nothing to warm"}))
        return 1
    synth_d = _synth_spec_dict_from_args(args)
    search_d = _search_spec_dict_from_args(args)
    if search_d is not None:
        # `warmup --search` (ISSUE 19): lower the pruned correlation
        # program against ShapeDtypeStructs — no bank build, no
        # campaign run — landing the persistent-cache entries a later
        # `process --batched --search` or served `search` job hits
        # warm.  --catalog warms every rung up to the campaign's (the
        # serve worker's any-epoch-count contract).
        if files:
            raise SystemExit("--search warmups take no template files "
                             "(the campaign + bank specs define the "
                             "program)")
        import jax

        from .search import warm_search

        sigs = warm_search(synth_d, search_d, _estimator_opts(args),
                           batch=args.batch,
                           catalog=getattr(args, "catalog", False))
        for sig in sigs:
            log_event(log, "warmup_signature", **sig)
        print(json.dumps({"cache_dir": cache, "jax": jax.__version__,
                          "backend": jax.default_backend(),
                          "signatures": sigs}))
        return 0
    synth_spec = genid = None
    epochs, failed = [], 0
    if synth_d is not None:
        # synthetic campaigns need no template files: the spec IS the
        # observing setup (axes + generator), and every planned step
        # signature is a uint32 key batch
        if files:
            raise SystemExit("--synthetic warmups take no template "
                             "files (the spec defines the setup)")
        from .sim import campaign

        synth_spec = campaign.spec_from_dict(synth_d)
        genid = campaign.generator_id(synth_spec)
    else:
        epochs, _names, failed, _quar = _load_clean_epochs(args, files,
                                                           log)
        if not epochs:
            print(json.dumps({"error": "no usable template epochs",
                              "failed": failed}))
            return 1
    pcfg = _pipeline_config_from_args(args)
    mesh_shape = getattr(args, "mesh", None)
    # the compiled signature INCLUDES the mesh: --no-mesh warms the
    # MESHLESS signatures a default `serve` worker executes (serve
    # without --mesh runs run_pipeline(mesh=None)), while the default
    # here mirrors `process --batched` (full local mesh)
    if getattr(args, "no_mesh", False):
        if mesh_shape:
            raise SystemExit("--no-mesh and --mesh are mutually "
                             "exclusive")
        mesh = None
    else:
        mesh = (make_mesh(tuple(int(x) for x in mesh_shape))
                if mesh_shape else make_mesh())
    chan = _resolve_chan_sharded(mesh, None)
    chunk = getattr(args, "chunk_epochs", None)
    pad_chunks = getattr(args, "pad_chunks", False)
    catalog = getattr(args, "catalog", False)
    plans = compile_cache.plan_steps(epochs, pcfg, mesh=mesh, chunk=chunk,
                                    pad_chunks=pad_chunks,
                                    batch=args.batch, catalog=catalog,
                                    synthetic=synth_spec)
    import jax

    from .parallel.driver import _SplitStep

    sigs = []
    keys = []
    for freqs, times, bshape, dtype, chunked in plans:
        donate = _resolve_donate(not getattr(args, "no_async", False),
                                 chunked, mesh)
        spec_sharding = (mesh_mod.data_sharding(mesh, chan)
                         if mesh is not None else None)
        spec = jax.ShapeDtypeStruct(
            tuple(bshape), jax.dtypes.canonicalize_dtype(dtype),
            sharding=spec_sharding)
        step = make_pipeline(freqs, times, pcfg, mesh=mesh,
                             chan_sharded=chan, donate=donate,
                             synth=synth_spec)
        if isinstance(step, _SplitStep):
            # split pipeline (ISSUE 14): each unit is its own artifact.
            # The back (fitter) unit's key is axes-free, so the SECOND
            # template/rung that maps onto it reports `cached` — one
            # warmed fitter set covers every survey shape.  Under a
            # >1-device mesh the back unit stays jit-served (its
            # artifact spec cannot describe the sharded handoff —
            # _SplitStep.back_aot_eligible), so only the front exports.
            units = [("front", step.front_key(tuple(bshape), dtype),
                      step.front, spec)]
            if step.back_aot_eligible():
                units.append(("back", step.back_key(int(bshape[0])),
                              step.back,
                              step.back_spec(int(bshape[0]))))
        else:
            units = [(None, compile_cache.step_key(
                freqs, times, pcfg, mesh, chan, bshape, dtype,
                donate=donate, synth=genid), step, spec)]
        sig = {"shape": list(bshape), "key": units[0][1]}
        if units[0][0] is not None:
            sig["units"] = {}
        t0 = time.perf_counter()
        for uname, ukey, ufn, uspec in units:
            keys.append(ukey)
            # --force first: a load under --force would memoize the
            # stale artifact and defeat the re-export
            fn = None if args.force else compile_cache.load_step(
                ukey, count=False)
            if fn is not None and hasattr(fn, "lower"):
                # StableHLO-only cache (written before the serialized-
                # executable layer existed): treat as uncached so the
                # .jaxexec fast path gets BACKFILLED — otherwise a
                # re-warm of an old cache ships an artifact whose
                # "warm" pods still pay the full XLA compile
                fn = None
            if fn is not None:
                status = "cached"
                # the AOT artifacts have no eviction pressure from XLA,
                # but the persistent XLA cache does: recompile the LIVE
                # step — its fingerprint is cross-process stable, so
                # this repairs an evicted entry for consumers that fall
                # back to the jit path; near-free on a warm cache
                ufn.lower(uspec).compile()
            else:
                # preferred artifact: the COMPILED executable (zero
                # retrace AND zero compile on load — the fresh-pod fast
                # path; its lower().compile() also lands the live
                # step's XLA entry in the persistent cache), plus the
                # StableHLO export as the portable fallback layer
                exec_path = compile_cache.export_executable(
                    ufn, bshape, dtype, ukey, sharding=spec_sharding,
                    spec=uspec)
                path = compile_cache.export_step(ufn, bshape, dtype,
                                                 ukey, spec=uspec)
                if exec_path is None and path is None:
                    # serialization unsupported for this step/sharding:
                    # still warm the persistent XLA cache via jit
                    status = "xla-cache-only"
                    ufn.lower(uspec).compile()
                else:
                    status = "exported"
                    arts = [os.path.basename(p)
                            for p in (exec_path, path) if p is not None]
                    if uname is None:
                        sig["artifacts"] = arts
                    if exec_path is None:
                        # executable layer unavailable: at least leave
                        # the live step's XLA entry for the jit fallback
                        ufn.lower(uspec).compile()
            if uname is None:
                sig["status"] = status
            else:
                sig["units"][uname] = {"key": ukey, "status": status}
        if "status" not in sig:
            sig["status"] = "/".join(u["status"]
                                     for u in sig["units"].values())
        sig["compile_s"] = round(time.perf_counter() - t0, 3)
        sigs.append(sig)
        log_event(log, "warmup_signature",
                  **{k: v for k, v in sig.items()
                     if k not in ("shape", "units")},
                  shape="x".join(str(s) for s in bshape))
    out = {"cache_dir": cache, "jax": jax.__version__,
           "backend": jax.default_backend(),
           "signatures": sigs, "failed_templates": failed}
    if catalog:
        # the catalog's identity, from the step keys themselves (axes +
        # config + versions all folded in): this is what the warm-cache
        # artifact (scripts/build_warm_cache.py) is keyed on
        from .buckets import catalog_digest

        out["catalog_digest"] = catalog_digest(keys)
    # hygiene: a warmup is the natural growth event — evict LRU entries
    # beyond the size cap (SCINT_COMPILE_CACHE_MAX_MB) right after it
    out["evictions"] = compile_cache.enforce_cache_cap()
    print(json.dumps(out))
    return 0


def cmd_serve(args) -> int:
    """Run a resident survey worker bound to a queue directory: claim
    leased jobs, dynamically batch compatible epochs onto the warm
    compiled step signatures (``run_pipeline(pad_to=batch)``), write
    idempotent content-keyed result rows, and requeue/poison failures
    (scintools_tpu.serve; docs/serving.md)."""
    from . import compile_cache
    from .parallel import make_mesh
    from .serve import JobQueue, ServeWorker, parse_lane_budgets
    from .utils import get_logger, log_event

    log = get_logger()
    queue = JobQueue(args.queue, max_retries=args.max_retries,
                     shards=getattr(args, "shards", None))
    compile_cache.enable_persistent_cache()
    mesh = (make_mesh(tuple(int(x) for x in args.mesh)) if args.mesh
            else None)
    try:
        budgets = (parse_lane_budgets(args.lane_budgets)
                   if getattr(args, "lane_budgets", None) else None)
        worker = ServeWorker(queue, batch_size=args.batch,
                             max_wait_s=args.max_wait, lease_s=args.lease,
                             poll_s=args.poll, mesh=mesh,
                             async_exec=not args.no_async,
                             bucket=getattr(args, "bucket", False),
                             heartbeat_s=getattr(args, "heartbeat", 10.0),
                             worker_id=getattr(args, "worker_id", None),
                             lane_budgets=budgets)
    except ValueError as e:
        # e.g. batch/mesh divisibility — a usage error, not a traceback
        raise SystemExit(str(e))
    try:
        with _xprof_ctx(getattr(args, "xprof", None)):
            stats = worker.run(max_batches=args.max_batches,
                               exit_on_drain=not args.ignore_drain,
                               idle_exit_s=args.idle_exit)
    except KeyboardInterrupt:
        # leased jobs are reclaimed by lease expiry; report honestly
        stats = dict(worker.stats)
        log_event(log, "serve_interrupted", **stats)
    if args.results:
        stats["csv_rows"] = queue.results.export_csv(
            args.results, full=getattr(args, "full_csv", False))
    print(json.dumps({"queue": args.queue, "worker": worker.worker_id,
                      **stats}))
    return 0 if stats["jobs_failed"] == 0 else 1


def cmd_submit(args) -> int:
    """Submit epoch files to a serve queue (idempotent per file
    content + estimator options); prints one JSON line with the job
    ids and their states."""
    from .serve import SurveyClient

    _validate_estimator_flags(args)
    files = _expand(args.files)
    client = SurveyClient(args.queue)
    synth_d = _synth_spec_dict_from_args(args)
    if getattr(args, "compact", False):
        # `compact` job kind: results-plane maintenance, no epochs
        if files or synth_d is not None \
                or getattr(args, "stream", None):
            raise SystemExit("--compact submits take no input files, "
                             "--synthetic campaign or --stream feed")
        rec = client.compact()
        print(json.dumps({"queue": args.queue, "submitted": 1,
                          "deduped": 0, "missing": 0,
                          "jobs": [{"file": "compact:",
                                    "job": rec["job"],
                                    "status": rec["status"]}]}))
        return 0
    lane = getattr(args, "lane", None)
    if getattr(args, "stream", None):
        # `stream` job kind: register a live append-mode feed — the
        # worker polls it between batch claims, publishing versioned
        # rows per sliding-window tick (docs/streaming.md)
        if files or synth_d is not None:
            raise SystemExit("--stream registrations take no input "
                             "files or --synthetic campaign")
        try:
            rec = client.submit_stream(
                args.stream, _estimator_opts(args),
                window=getattr(args, "stream_window", None),
                hop=getattr(args, "stream_hop", None), lane=lane,
                incremental=(True if getattr(args, "stream_incremental",
                                             False) else None),
                resync_every=getattr(args, "stream_resync", None))
        except ValueError as e:
            raise SystemExit(str(e))
        print(json.dumps({
            "queue": args.queue,
            "submitted": 1 if rec["status"] == "submitted" else 0,
            "deduped": 0 if rec["status"] == "submitted" else 1,
            "missing": 0,
            "jobs": [{"file": f"stream:{rec['feed']}",
                      "job": rec["job"], "status": rec["status"]}]}))
        return 0
    if synth_d is not None:
        # `simulate` job kind: one job = one on-device campaign (no
        # input files; keys + params ARE the job payload).  Defaults
        # onto the BULK lane unless --lane says otherwise.  With
        # --infer it becomes the `infer` job kind: the same campaign
        # payload plus the optimiser knobs, fitted by gradient descent
        # through the compiled simulator (docs/inference.md).
        if files:
            raise SystemExit("--synthetic submits take no input files")
        infer_d = _infer_spec_dict_from_args(args)
        search_d = _search_spec_dict_from_args(args)
        if infer_d is not None:
            try:
                rec = client.submit_infer(synth_d, infer_d,
                                          _estimator_opts(args),
                                          lane=lane)
            except ValueError as e:
                raise SystemExit(str(e))
            recs = [{"file": f"infer:{synth_d.get('kind', 'screen')}",
                     "job": rec["job"], "status": rec["status"]}]
        elif search_d is not None:
            # `search` job kind (ISSUE 19): the same campaign payload
            # plus the bank/pruning knobs, scored by Fourier-domain
            # matched filtering against the resident template bank
            # (docs/search.md)
            try:
                rec = client.submit_search(synth_d, search_d,
                                           _estimator_opts(args),
                                           lane=lane)
            except ValueError as e:
                raise SystemExit(str(e))
            recs = [{"file": f"search:{synth_d.get('kind', 'screen')}",
                     "job": rec["job"], "status": rec["status"]}]
        else:
            rec = client.submit_synthetic(synth_d, _estimator_opts(args),
                                          lane=lane)
            recs = [{"file": f"synthetic:"
                             f"{synth_d.get('kind', 'screen')}",
                     "job": rec["job"], "status": rec["status"]}]
    else:
        if not files:
            raise SystemExit("no input files (pass psrflux files, or "
                             "--synthetic N for a simulate job)")
        recs = client.submit(files, _estimator_opts(args), lane=lane)
    fresh = sum(1 for r in recs if r["status"] == "submitted")
    missing = sum(1 for r in recs if r["status"] == "missing")
    base = {"queue": args.queue, "submitted": fresh,
            "deduped": len(recs) - fresh - missing, "missing": missing,
            "jobs": recs}
    if args.wait is not None:
        waited = client.wait([r["job"] for r in recs
                              if r["job"] is not None],
                             timeout=args.wait)
        print(json.dumps({**base, "done": len(waited["done"]),
                          "failed": len(waited["failed"]),
                          "pending": len(waited["pending"])}))
        return 0 if not (waited["failed"] or waited["pending"]
                         or missing) else 1
    print(json.dumps(base))
    return 0 if missing == 0 else 1


def cmd_pool(args) -> int:
    """Run the fleet pool controller over a serve queue directory:
    spawn `serve` worker subprocesses when the merged-heartbeat
    backpressure crosses the high-water threshold, drain one (the
    per-worker drain marker) below the low-water one, replace
    STALE-heartbeat workers, and publish warm/memory-affinity claim
    hints the workers honour at claim time (scintools_tpu.serve.pool;
    docs/fleet.md)."""
    from .serve import parse_lane_budgets
    from .serve.pool import PoolConfig, PoolController
    from .utils import get_logger, log_event

    log = get_logger()
    try:
        cfg = PoolConfig(min_workers=args.min, max_workers=args.max,
                         high_water=args.high, low_water=args.low,
                         cooldown_s=args.cooldown, poll_s=args.poll)
    except ValueError as e:
        raise SystemExit(str(e))
    if args.heartbeat <= 0:
        # the controller's WHOLE signal set (drain rate, warm sigs,
        # headroom, staleness) rides the heartbeats; a heartbeat-less
        # pool would read every worker as dead forever
        raise SystemExit("pool workers must heartbeat: --heartbeat "
                         "must be > 0")
    # curated pass-through: the worker knobs a pool operator tunes
    worker_args: list[str] = ["--batch", str(args.batch),
                              "--max-wait", str(args.max_wait),
                              "--lease", str(args.lease),
                              "--heartbeat", str(args.heartbeat)]
    if args.bucket:
        worker_args.append("--bucket")
    if args.lane_budgets:
        try:
            parse_lane_budgets(args.lane_budgets)   # fail fast HERE
        except ValueError as e:
            raise SystemExit(str(e))
        worker_args += ["--lane-budgets", args.lane_budgets]
    ctl = PoolController(args.queue, cfg, worker_args=worker_args)
    try:
        stats = ctl.run(max_rounds=args.rounds)
    except KeyboardInterrupt:
        stats = dict(ctl.stats)
        log_event(log, "pool_interrupted", **stats)
    finally:
        ctl.shutdown()
    print(json.dumps({"queue": args.queue, **stats}))
    return 0


def _existing_queue_dir(qdir: str) -> str:
    """status/drain are read-side verbs: a mistyped path must error,
    not silently create a fresh empty queue tree (whose all-zero
    counts would read as 'survey done' — or worse, whose planted
    drain marker would stop the next worker started there)."""
    import os

    if not os.path.isdir(qdir):
        raise SystemExit(f"{qdir}: no such queue directory (submit or "
                         "serve creates one)")
    return qdir


def cmd_queue_status(args) -> int:
    """One JSON line of queue state counts (queued/leased/done/failed),
    stored result rows, depth, and drain flag."""
    from .serve import SurveyClient

    print(json.dumps({"queue": args.queue,
                      **SurveyClient(
                          _existing_queue_dir(args.queue)).status()}))
    return 0


def cmd_drain(args) -> int:
    """Request worker drain (finish the queue, then exit) and — with
    ``--timeout`` — wait for the queue to empty."""
    from .serve import SurveyClient

    client = SurveyClient(_existing_queue_dir(args.queue))
    st = client.drain(timeout=args.timeout)
    if args.results:
        st["csv_rows"] = client.export_csv(
            args.results, full=getattr(args, "full_csv", False),
            latest_only=getattr(args, "latest_only", False))
    print(json.dumps({"queue": args.queue, **st}))
    return 0 if st["drained"] or args.timeout is None else 1


def cmd_sort(args) -> int:
    from .pipeline import sort_dyn

    good, bad = sort_dyn(_expand(args.files), outdir=args.outdir,
                         min_nsub=args.min_nsub, min_nchan=args.min_nchan,
                         min_freq=args.min_freq, max_freq=args.max_freq,
                         verbose=args.verbose)
    print(json.dumps({"good": len(good), "bad": len(bad)}))
    return 0


def cmd_sim(args) -> int:
    from .io import from_simulation
    from .io.psrflux import write_psrflux
    from .sim import Simulation

    def one(seed, out):
        sim = Simulation(mb2=args.mb2, rf=args.rf, ds=args.ds,
                         alpha=args.alpha, ar=args.ar, psi=args.psi,
                         inner=args.inner, ns=args.ns, nf=args.nf,
                         dlam=args.dlam, seed=seed, backend=args.backend)
        d = from_simulation(sim, freq=args.freq, dt=args.dt)
        write_psrflux(d, out)
        return d

    n = int(getattr(args, "ensemble", 1) or 1)
    if n <= 1:
        d = one(args.seed, args.out)
        print(json.dumps({"out": args.out, "nchan": d.nchan,
                          "nsub": d.nsub}))
        return 0
    # seeded survey: N epochs <stem>_KKKK<ext>, consumable directly by
    # `process --batched` (equal grids -> one compiled step)
    import os

    if args.seed is None:
        # match single-run semantics (no --seed = independent randoms):
        # a fresh random base per invocation, reported for reproduction
        import numpy as np

        base = int(np.random.SeedSequence().entropy % (2 ** 31))
    else:
        base = int(args.seed)
    stem, ext = os.path.splitext(args.out)
    ext = ext or ".dynspec"
    files = []
    for i in range(n):
        out = f"{stem}_{i:04d}{ext}"
        one(base + i, out)
        files.append(out)
    print(json.dumps({"out": f"{stem}_*{ext}", "files": n,
                      "seed_base": base}))
    return 0


def cmd_curvature(args) -> int:
    """Fit physical screen parameters to a survey's curvature series.

    The reference ships the ``arc_curvature`` residual model but leaves
    the actual annual-variation fit to user notebooks; this completes
    the workflow: results CSV (from ``process --lamsteps``) + par file
    -> screen fraction / velocity / anisotropy with errors, as JSON.
    """
    import numpy as np

    from .fit import fit_arc_curvature
    from .io.parfile import pars_to_params, read_par
    from .io.results import float_array_from_dict, read_results

    res = read_results(args.results)
    if "betaeta" not in res:
        raise SystemExit(
            "curvature fitting needs the 'betaeta' column (lamsteps "
            "curvature, 1/(m mHz^2) — the model's units); run "
            "process --lamsteps to produce it")
    mjd = float_array_from_dict(res, "mjd")
    eta = float_array_from_dict(res, "betaeta")
    etaerr = (float_array_from_dict(res, "betaetaerr")
              if "betaetaerr" in res else None)
    keep = np.isfinite(mjd) & np.isfinite(eta) & (eta > 0)
    if etaerr is not None:
        keep &= np.isfinite(etaerr) & (etaerr > 0)
    if int(keep.sum()) < len(args.fit) + 1:
        raise SystemExit(f"only {int(keep.sum())} usable epochs in "
                         f"{args.results} for {len(args.fit)} fitted "
                         "parameters")
    mjd, eta = mjd[keep], eta[keep]
    if etaerr is not None:
        etaerr = etaerr[keep]

    pars = pars_to_params(read_par(args.par))
    raj, decj = pars.get("RAJ"), pars.get("DECJ")
    if raj is None or decj is None:
        raise SystemExit(f"{args.par} needs RAJ/DECJ (source position "
                         "for the Earth-velocity projection)")
    # screen starting values: par-file distance if present, then --start
    _SCREEN_KEYS = ("s", "d", "psi", "vism_psi", "vism_ra", "vism_dec")
    pars.setdefault("d", float(pars.get("DIST", 1.0)))
    pars.setdefault("s", 0.5)
    for k in args.fit:
        if k.startswith("vism_"):
            pars.setdefault(k, 0.0)
    if "psi" in args.fit:
        pars.setdefault("psi", 45.0)   # start only; optimised away
    user_start = set()
    for kv in args.start or []:
        k, sep, v = kv.partition("=")
        if not sep or k not in _SCREEN_KEYS:
            raise SystemExit(
                f"--start takes KEY=VALUE pairs with KEY in "
                f"{'/'.join(_SCREEN_KEYS)}, got {kv!r}")
        try:
            pars[k] = float(v)
        except ValueError:
            raise SystemExit(f"--start {k}: {v!r} is not a number")
        user_start.add(k)
    # The model has two mutually exclusive screen-velocity branches
    # (models/velocity.py): psi present -> ANISOTROPIC, reads vism_psi
    # only; psi absent -> isotropic, reads vism_ra/vism_dec only.
    # Reject every combination where a user-supplied velocity would be
    # silently ignored, instead of fitting a dead parameter.
    wants = lambda k: k in args.fit or k in user_start  # noqa: E731
    aniso = wants("vism_psi")
    iso = wants("vism_ra") or wants("vism_dec")
    if aniso and iso:
        raise SystemExit(
            "vism_psi (anisotropic screen) and vism_ra/vism_dec "
            "(isotropic screen) are mutually exclusive model branches; "
            "use one or the other")
    if aniso and "psi" not in pars:
        raise SystemExit(
            "using vism_psi needs the anisotropy axis psi: pass "
            "--start psi=<deg> (fixed) or add psi to --fit")
    if iso and "psi" in pars:
        raise SystemExit(
            "psi selects the anisotropic branch, which ignores "
            "vism_ra/vism_dec; drop psi or fit vism_psi instead")

    best, errors, fitres = fit_arc_curvature(
        eta, mjd, pars, raj, decj, fit_keys=tuple(args.fit),
        etaerr=etaerr, backend=args.backend)

    def _num(x):
        # strict machine-readable stdout: a singular covariance yields
        # inf/NaN stderr, which json.dumps would emit as invalid JSON
        x = float(x)
        return x if np.isfinite(x) else None

    print(json.dumps({
        "n_epochs": int(len(mjd)),
        "fit": {k: {"value": _num(best[k]), "err": _num(errors[k])}
                for k in args.fit},
        "cost": _num(np.asarray(fitres.cost)),
    }, allow_nan=False))

    if args.plot:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        from .astro import get_earth_velocity, get_true_anomaly
        from .models.velocity import arc_curvature_model

        grid = np.linspace(mjd.min(), mjd.max(), 500)
        nu = (get_true_anomaly(grid, best) if "PB" in best
              else np.zeros_like(grid))
        v_ra, v_dec = get_earth_velocity(grid, raj, decj)
        model = arc_curvature_model(best, nu, v_ra, v_dec)
        fig, ax = plt.subplots(figsize=(8, 4))
        if etaerr is not None:
            ax.errorbar(mjd, eta, yerr=etaerr, fmt="o", ms=4,
                        label="measured")
        else:
            ax.plot(mjd, eta, "o", ms=4, label="measured")
        ax.plot(grid, model, "-", label="screen model")
        ax.set_xlabel("MJD")
        ax.set_ylabel(r"$\beta$-curvature (1/(m mHz$^2$))")
        ax.legend()
        fig.tight_layout()
        fig.savefig(args.plot, dpi=120)
        plt.close(fig)
    return 0


def cmd_wavefield(args) -> int:
    import numpy as np

    from .backend import resolve
    from .pipeline import Dynspec

    files = _expand(args.files)
    if args.out and len(files) != 1:
        print(f"--out needs exactly one input file (got {len(files)}); "
              f"omit it to write per-file <name>.wavefield.npz",
              file=sys.stderr)
        return 1
    if args.plots:
        import matplotlib

        matplotlib.use("Agg")

    # phase 1: load + process + curvature per file.  Only the light
    # DynspecData survives this loop (the Dynspec wrapper's ACF/sspec
    # caches are dropped with it) — grouping needs all epochs' grids
    # before any retrieval can be batched.
    epochs, rc = [], 0
    for fn in files:
        try:
            ds = Dynspec(filename=fn, process=True, backend=args.backend)
            if args.eta is not None:
                eta = float(args.eta)
            else:
                ds.fit_arc(method="thetatheta", lamsteps=False,
                           etamin=args.etamin, etamax=args.etamax,
                           numsteps=args.numsteps)
                eta = float(ds.eta)
            epochs.append((fn, ds.data, eta))
        except Exception as e:
            print(f"{fn}: wavefield retrieval failed ({e})",
                  file=sys.stderr)
            rc = 1

    def persist(fn, data, eta, wf, nbatch) -> None:
        from .fit.wavefield import intensity_corr

        corr = intensity_corr(wf.field, data.dyn)
        base = fn.rsplit(".", 1)[0]
        out = args.out if args.out else f"{base}.wavefield.npz"
        wf.save(out)
        if args.plots:
            import matplotlib.pyplot as plt

            from . import plotting

            plotting.plot_wavefield(wf, filename=f"{base}.wavefield.png")
            plotting.plot_sspec(wf.secspec(), eta=eta,
                                filename=f"{base}.wavefield_sspec.png")
            plt.close("all")
        print(json.dumps({
            "file": fn, "eta": eta,
            "corr": round(corr, 4) if np.isfinite(corr) else None,
            # corr is of the PERSISTED field; when the (default) auto
            # rule applied the global refinement, intensity corr can
            # legitimately DROP while the phases improve (docs/
            # wavefield.md "WEAKER metric" note) — refined_global says
            # whether that happened
            "refined_global": int(wf.refined_global),
            "conc_mean": round(float(wf.conc.mean()), 4),
            "ntheta": len(wf.theta), "batch": nbatch, "out": out}))

    # phase 2: retrieval + streaming persist per group — equal-grid
    # epochs on the jax backend go through retrieve_wavefield_batch
    # (every chunk of every epoch in ONE compiled program); others stay
    # per-file, each isolated in its own try
    from .fit.wavefield import retrieve_wavefield, \
        retrieve_wavefield_batch

    groups: dict = {}
    for item in epochs:
        f = np.asarray(item[1].freqs, dtype=np.float64)
        t = np.asarray(item[1].times, dtype=np.float64)
        groups.setdefault((f.shape, t.shape, f.tobytes(), t.tobytes()),
                          []).append(item)
    kw = dict(chunk_nf=args.chunk, chunk_nt=args.chunk,
              conc_weight=args.conc_weight, refine=args.refine,
              refine_global=args.refine_global)
    for group in groups.values():
        if resolve(args.backend) == "jax" and len(group) > 1:
            try:
                d0 = group[0][1]
                wfs = retrieve_wavefield_batch(
                    np.stack([np.asarray(d.dyn, dtype=np.float64)
                              for _, d, _ in group]),
                    np.asarray(d0.freqs), np.asarray(d0.times),
                    [eta for _, _, eta in group], freq=float(d0.freq),
                    dt=float(d0.dt), df=float(d0.df), backend="jax",
                    **kw)
                for (fn, d, eta), wf in zip(group, wfs):
                    try:
                        persist(fn, d, eta, wf, len(group))
                    except Exception as e:
                        print(f"{fn}: wavefield output failed ({e})",
                              file=sys.stderr)
                        rc = 1
                continue
            except Exception as e:
                # the batching itself can be the failure (one epoch's
                # degenerate eta, batch OOM): fall back to independent
                # per-file retrieval instead of failing the whole group
                print(f"batched retrieval failed ({e}); retrying "
                      f"{len(group)} file(s) individually",
                      file=sys.stderr)
        for fn, d, eta in group:
            try:
                persist(fn, d, eta,
                        retrieve_wavefield(d, eta,
                                           backend=args.backend, **kw),
                        1)
            except Exception as e:
                print(f"{fn}: wavefield retrieval failed ({e})",
                      file=sys.stderr)
                rc = 1
    return rc


def cmd_trace_report(args) -> int:
    """Aggregate JSONL trace(s) — literal paths and/or globs — into
    the merged per-stage count/total/p50/p95 table plus counters.
    Torn/truncated lines and an unreadable file among several degrade
    to a stderr warning, never a mid-report traceback.  ``--fleet``
    treats each argument as a fleet directory (heartbeats + traces +
    crash flights) and appends the merged rollup + backpressure."""
    import os

    try:
        since = (obs.parse_when(args.since)
                 if getattr(args, "since", None) else None)
        last = (obs.parse_duration(args.last)
                if getattr(args, "last", None) else None)
    except ValueError as e:
        raise SystemExit(str(e))
    if getattr(args, "fleet", False):
        if since is not None or last is not None:
            raise SystemExit("--since/--last filter trace records; the "
                             "--fleet rollup reads live heartbeats "
                             "(stale workers are flagged instead)")
        rc = 0
        for d in args.tracefile:
            if not os.path.isdir(d):
                print(f"{d}: no such fleet directory", file=sys.stderr)
                rc = 1
                continue
            text, warnings = obs.fleet_report(d)
            for w in warnings:
                print(f"warning: {w}", file=sys.stderr)
            print(text)
        return rc
    try:
        text, warnings = obs.report_many(list(args.tracefile),
                                         since=since, last=last)
    except (OSError, UnicodeDecodeError) as e:
        # a binary file (e.g. a .dynspec passed by mistake) or nothing
        # readable at all fails with a one-line error, not a traceback
        print(f"{', '.join(args.tracefile)}: unreadable ({e})",
              file=sys.stderr)
        return 1
    for w in warnings:
        print(f"warning: {w}", file=sys.stderr)
    print(text)
    return 0


def cmd_fleet_status(args) -> int:
    """Fleet rollup over a serve queue directory: per-worker heartbeat
    rows, merged histograms, reassembled traces, and the backpressure
    scalar (docs/observability.md).  ``--json`` prints the machine
    form (the admission-control input)."""
    from .obs import fleet as fleet_mod

    qdir = _existing_queue_dir(args.queue)
    # live queue-side readouts (depth, per-shard/per-lane backlogs,
    # pool-controller snapshot) beat the heartbeat-reported ones when
    # the dir IS a queue (bare heartbeat dirs have no queued/ subdir)
    extras = fleet_mod.queue_extras(qdir)
    heartbeats, events, warnings = fleet_mod.collect_fleet(qdir)
    for w in warnings:
        print(f"warning: {w}", file=sys.stderr)
    rollup = fleet_mod.fleet_rollup(heartbeats, events,
                                    depth=extras.get("depth"))
    rollup.update(extras)
    fleet_mod.attach_slo_status(rollup, heartbeats)
    if args.json:
        print(json.dumps({"queue": args.queue, **rollup}, default=str))
    else:
        print(fleet_mod.render_fleet(rollup))
    return 0


def cmd_alerts(args) -> int:
    """Durable SLO alert rows of a serve queue directory (obs/slo,
    docs/slo.md): list them firing-first, ``--ack`` one (a versioned
    newest-wins write that survives worker crashes), or print a row's
    retained ``--history`` of state transitions."""
    import os

    from .obs import slo as slo_mod
    from .utils.store import ResultsStore

    qdir = _existing_queue_dir(args.queue)
    if args.ack:
        store = ResultsStore(os.path.join(qdir, "results"))
        engine = slo_mod.AlertEngine(store)
        row = engine.ack(args.ack)
        if row is None:
            print(f"{args.ack}: no such alert row", file=sys.stderr)
            return 1
        print(f"{args.ack}: acked (state = {row['state']})")
        return 0
    rows = slo_mod.read_alerts(qdir)
    if args.history:
        match = [r for r in rows if r.get("slo") == args.history]
        if not match:
            print(f"{args.history}: no such alert row", file=sys.stderr)
            return 1
        row = match[0]
        if args.json:
            print(json.dumps(row, default=str))
            return 0
        print(f"{row['slo']}: state = {row['state']}"
              + (" (acked)" if row.get("ack") else ""))
        for ts, state in row.get("history", ()):
            print(f"  {float(ts):.3f}  {state}")
        return 0
    if args.json:
        print(json.dumps({"queue": args.queue, "alerts": rows},
                         default=str))
        return 0
    if not rows:
        print("(no alert rows — no slo.json declared, or the plane "
              "has not evaluated yet)")
        return 0
    for row in rows:
        line = f"{row['slo']}: {row['state']}"
        if row.get("metric"):
            line += f"  [{row['metric']}]"
        bf, bs = row.get("burn_fast"), row.get("burn_slow")
        if isinstance(bf, (int, float)) and isinstance(bs, (int, float)):
            line += f"  burn fast/slow = {bf:g}/{bs:g}"
        if row.get("ack"):
            line += "  (acked)"
        if row.get("trace_id"):
            line += f"  trace={row['trace_id']}"
        print(line)
    return 0


def cmd_fsck(args) -> int:
    """Crash-consistency audit of a serve queue directory
    (serve/fsck.py, invariant catalog in docs/reliability.md):
    dry-run by default, ``--repair`` applies only the planes' own
    proven recovery actions.  Exit 0 when clean (every invariant
    holds, or every finding repaired), 1 otherwise."""
    from .serve import fsck as fsck_mod

    qdir = _existing_queue_dir(args.queue)
    report = fsck_mod.run_fsck(qdir, repair=args.repair)
    if args.json:
        print(json.dumps(report, default=str))
    else:
        print(fsck_mod.render_report(report))
    return 0 if report["clean"] else 1


def cmd_bench(args) -> int:
    # bench.py lives at the repo root (the driver contract), not in the
    # installed package: load it by path relative to this package, falling
    # back to a plain import for checkout layouts with cwd on sys.path.
    import importlib.util
    import os

    if getattr(args, "trace", None):
        # bench runs its fallback/probe phases in fresh subprocesses
        # that enable tracing from this env var (bench._maybe_enable_
        # trace); without it the subprocess spans of the very run being
        # diagnosed would silently miss the trace file.  Unconditional
        # assignment: an ambient SCINT_BENCH_TRACE from earlier
        # experimentation must not divert this run's spans elsewhere.
        # abspath: the fallback subprocess runs with cwd=repo-root, so a
        # relative path would silently split the trace across two files.
        os.environ["SCINT_BENCH_TRACE"] = os.path.abspath(args.trace)
    if getattr(args, "xprof", None):
        # bench reads the env (its measure window lives in bench.py's
        # device_throughput); abspath for the same cwd reason as above
        os.environ["SCINT_BENCH_XPROF"] = os.path.abspath(args.xprof)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "bench.py")
    if os.path.exists(path):
        spec = importlib.util.spec_from_file_location("bench", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.main()
        return 0
    try:
        import bench
    except ImportError:
        print("bench.py not found (run from a repo checkout)",
              file=sys.stderr)
        return 1
    bench.main()
    return 0


def _add_perf_policy_flags(q) -> None:
    """The batched engine's precision/FFT-length/crop knobs — one
    definition shared by process/warmup/submit so a warmed or served
    config always matches what a survey would run (the knobs enter the
    resume key, the compile-cache key and the serve job identity)."""
    q.add_argument("--precision", default="f32",
                   choices=["f32", "bf16_io"],
                   help="batched-engine I/O precision: bf16_io "
                        "transfers + holds the dynspec batch in "
                        "bfloat16 (half the H2D bytes and first-stage "
                        "reads) with f32 compute; parity budget in "
                        "docs/performance.md")
    q.add_argument("--fft-lens", default="pow2", dest="fft_lens",
                   choices=["pow2", "fast"],
                   help="secondary-spectrum FFT padding: pow2 = the "
                        "reference's next-pow2-doubled rule (parity); "
                        "fast = smallest even 2^a*3^b*5^c composite "
                        ">= 2n per axis (never longer, often much "
                        "shorter for non-pow2 epochs)")
    q.add_argument("--sspec-crop", action="store_true", dest="sspec_crop",
                   help="fuse the arc fitter's delay-window crop into "
                        "the compiled step (norm_sspec only): the "
                        "spectrum tail beyond the fitted window is "
                        "never materialised; eta identical, etaerr's "
                        "noise window shrinks to the cropped grid")
    q.add_argument("--fused-sspec", action="store_true",
                   dest="fused_sspec",
                   help="fused secondary-spectrum kernels (Pallas on "
                        "TPU): prologue/epilogue run as single fused "
                        "passes and, with --sspec-crop, the delay "
                        "transform shrinks to the kept rows (measured "
                        "-36%% sspec-stage HBM bytes at 256x512); "
                        "opt-in — fits agree within the 2%% budget, "
                        "not bit-identical")
    q.add_argument("--split-programs", action="store_true",
                   dest="split_programs",
                   help="compile the batched step as TWO separately "
                        "cached program units: a shape-volatile "
                        "front-end (transforms) and a shape-stable "
                        "fitter back-end keyed on canonicalised "
                        "intermediate lengths — a novel (nf, nt) "
                        "recompiles only the front slice (back-end "
                        "jit_cache_miss stays 0).  Results are "
                        "bit-identical to the fused step; a placement "
                        "knob, never part of the job identity or "
                        "resume key")


def _add_synth_flags(q) -> None:
    """The zero-H2D synthetic-campaign flags — one definition shared by
    process/warmup/submit, so the campaign identity (resume key,
    compile-cache key, serve job identity) is built from the same spec
    everywhere (`_synth_spec_dict_from_args`)."""
    q.add_argument("--synthetic", type=int, default=None, metavar="N",
                   help="run an N-epoch on-device synthetic campaign "
                        "instead of loading files: the compiled step's "
                        "input is the PRNG key batch and the dynspec "
                        "batch is generated in device memory (zero H2D "
                        "traffic in the hot loop; batched engine only)")
    q.add_argument("--synth-kind", default="screen",
                   choices=["screen", "arc", "acf"],
                   help="generator: Kolmogorov phase screens "
                        "(physics), thin-arc images (closed-form "
                        "injected curvature), or exact-model-ACF "
                        "fields (injected tau/dnu)")
    q.add_argument("--synth-seed", type=int, default=0,
                   help="campaign base seed (epoch i's key is "
                        "[seed, i])")
    q.add_argument("--synth-nf", type=int, default=None,
                   help="channels (screen: SimParams.nf; arc/acf: nf)")
    q.add_argument("--synth-nt", type=int, default=None,
                   help="time samples (screen: SimParams.nx=ny; "
                        "arc/acf: nt)")
    q.add_argument("--synth-dt", type=float, default=None,
                   help="time step in seconds (default 8)")
    q.add_argument("--synth-df", type=float, default=None,
                   help="arc/acf channel width in MHz (default 0.5)")
    q.add_argument("--synth-freq", type=float, default=None,
                   help="observing frequency in MHz (default 1400)")
    q.add_argument("--synth-mb2", type=float, default=None,
                   help="screen kind: scattering strength (Born mb2)")
    q.add_argument("--synth-dlam", type=float, default=None,
                   help="screen kind: fractional bandwidth")
    q.add_argument("--synth-pac", action="store_true",
                   help="screen kind: Gaussian phase-autocovariance "
                        "low-frequency compensation (SimParams.pac)")
    q.add_argument("--synth-tau", type=float, default=None,
                   help="acf kind: injected 1/e timescale (s)")
    q.add_argument("--synth-dnu", type=float, default=None,
                   help="acf kind: injected half-power bandwidth (MHz)")


def _add_infer_flags(q) -> None:
    """The gradient-inference flags (ISSUE 18) — one definition shared
    by process/submit, so the optimiser identity (resume key, serve
    job identity) is built from the same spec everywhere
    (`_infer_spec_dict_from_args`)."""
    q.add_argument("--infer", action="store_true",
                   help="fit the --synthetic campaign's physics by "
                        "gradient descent THROUGH the compiled "
                        "simulator (vmapped multi-start Adam + Fisher "
                        "errors, one device program; arc/acf kinds — "
                        "docs/inference.md)")
    q.add_argument("--infer-steps", type=int, default=None,
                   dest="infer_steps", metavar="N",
                   help="Adam iteration ceiling per epoch (default "
                        "400; compiled in — the runtime budget "
                        "tightens it without recompiling)")
    q.add_argument("--infer-starts", type=int, default=None,
                   dest="infer_starts", metavar="S",
                   help="multi-start lanes per epoch (default 8; best "
                        "finite loss wins)")
    q.add_argument("--infer-lr", type=float, default=None,
                   dest="infer_lr", help="Adam learning rate in the "
                                         "unconstrained parameter "
                                         "space (default 0.05)")
    q.add_argument("--infer-tol", type=float, default=None,
                   dest="infer_tol",
                   help="per-lane gradient-norm convergence tolerance "
                        "(default 1e-3)")
    q.add_argument("--infer-spread", type=float, default=None,
                   dest="infer_spread",
                   help="multi-start lattice spread around the "
                        "data-driven init (default 0.25)")
    q.add_argument("--infer-seed", type=int, default=None,
                   dest="infer_seed",
                   help="start-lattice seed (default 0; deterministic "
                        "host-side lattice, never runtime RNG)")


def _add_search_flags(q) -> None:
    """The acceleration-search flags (ISSUE 19) — one definition
    shared by process/warmup/submit, so the bank identity (resume key,
    serve job identity) is built from the same spec everywhere
    (`_search_spec_dict_from_args`)."""
    q.add_argument("--search", action="store_true",
                   help="score the --synthetic campaign's secondary "
                        "spectra against an HBM-resident bank of "
                        "curvature-trial templates (Fourier-domain "
                        "matched filter, coarse-to-fine pruning; "
                        "docs/search.md)")
    q.add_argument("--search-trials", type=int, default=None,
                   dest="search_trials", metavar="J",
                   help="curvature trials in the bank (default 256, "
                        "geometric spacing)")
    q.add_argument("--search-eta-min", type=float, default=None,
                   dest="search_eta_min", metavar="ETA",
                   help="lowest trial curvature, us/mHz^2 (default: "
                        "auto range derived from the grid; set both "
                        "bounds or neither)")
    q.add_argument("--search-eta-max", type=float, default=None,
                   dest="search_eta_max", metavar="ETA",
                   help="highest trial curvature, us/mHz^2")
    q.add_argument("--search-width", type=float, default=None,
                   dest="search_width",
                   help="template ridge sigma in Doppler pixels "
                        "(default 1.0)")
    q.add_argument("--search-rows", type=int, default=None,
                   dest="search_rows", metavar="R",
                   help="delay rows scored (default nrfft/4 — the "
                        "crop-split window)")
    q.add_argument("--search-min-row", type=int, default=None,
                   dest="search_min_row", metavar="R0",
                   help="zero template rows below this delay row "
                        "(default 1: skip the DC self-power row)")
    q.add_argument("--search-top-k", type=int, default=None,
                   dest="search_top_k", metavar="K",
                   help="fine-pass survivors per epoch (default 16; "
                        "the compiled ceiling — the runtime budget "
                        "tightens it without recompiling)")
    q.add_argument("--search-decim", type=int, default=None,
                   dest="search_decim", metavar="D",
                   help="coarse-pass Fourier-bin decimation (default "
                        "8; the compiled grid — the runtime budget "
                        "coarsens it without recompiling)")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="scintools-tpu",
        description="TPU-native pulsar scintillation analysis")
    p.add_argument("--trace", metavar="OUT.jsonl", default=None,
                   help="enable pipeline tracing for this invocation and "
                        "append span/counter events (one JSON per line) "
                        "to this file; inspect with "
                        "`scintools-tpu trace report OUT.jsonl`")
    sub = p.add_subparsers(dest="command", required=True)

    q = sub.add_parser("info", help="print observation metadata")
    q.add_argument("files", nargs="+")
    q.set_defaults(fn=cmd_info)

    q = sub.add_parser("process",
                       help="process epochs: clean -> acf/sspec -> fits")
    q.add_argument("files", nargs="*",
                   help="psrflux epoch files (omit with --synthetic)")
    q.add_argument("--lamsteps", action="store_true")
    q.add_argument("--backend", default="numpy",
                   choices=["numpy", "jax"])
    q.add_argument("--results", help="append-mode CSV output")
    q.add_argument("--store", help="resumable per-epoch results dir")
    q.add_argument("--plots", help="write summary plots to this dir")
    q.add_argument("--no-arc", action="store_true")
    q.add_argument("--no-scint", action="store_true")
    q.add_argument("--scint-2d", action="store_true",
                   help="also fit the 2-D ACF model (phase-gradient "
                        "tilt -> store rows; per-file and batched)")
    q.add_argument("--mcmc", action="store_true",
                   help="posterior scint parameters via ensemble MCMC "
                        "(per-file engine; with --plots also writes a "
                        "<name>_corner.png posterior plot)")
    q.add_argument("--arc-asymm", action="store_true",
                   help="also measure per-arm curvatures "
                        "(eta_left/eta_right; batched mode)")
    q.add_argument("--arc-method", default="norm_sspec",
                   choices=["norm_sspec", "gridmax", "thetatheta"],
                   help="curvature estimator, per-file and batched "
                        "(thetatheta requires --arc-bracket)")
    q.add_argument("--arc-bracket", type=float, nargs=2, default=None,
                   metavar=("LO", "HI"),
                   help="curvature bracket: the peak-search constraint "
                        "(norm_sspec/gridmax) or the sweep range "
                        "(thetatheta)")
    q.add_argument("--arc-stack", action="store_true",
                   help="ALSO measure one campaign curvature per "
                        "shape-bucket by nanmean-stacking the epochs' "
                        "normalised profiles before the arc fit "
                        "(norm_sspec, batched mode; weak-arc S/N grows "
                        "as sqrt(epochs); written to store meta + log)")
    q.add_argument("--clean", action="store_true",
                   help="RFI/gain cleaning between load and the fits: "
                        "per-channel robust triage (zap method="
                        "'channels'), gap repair, bandpass removal — "
                        "for real survey data with receiver "
                        "pathologies (both engines; enters the resume "
                        "key)")
    q.add_argument("--batched", action="store_true",
                   help="one jit-compiled step per shape bucket over the "
                        "device mesh instead of a per-file loop")
    q.add_argument("--full-csv", action="store_true",
                   help="with --store + --results: export EVERY store "
                        "column (tilt, per-arm curvatures, ...) instead "
                        "of the reference-compatible schema")
    q.add_argument("--chunk-epochs", type=int, default=None,
                   help="batched mode: bound device memory by limiting "
                        "epochs per step (adjusted to a multiple of the "
                        "mesh's data-axis size, with a warning)")
    q.add_argument("--pad-chunks", action="store_true",
                   help="batched mode with --chunk-epochs: pad the final "
                        "uneven chunk up to the chunk size (mask-sliced "
                        "on gather) so the survey compiles exactly ONE "
                        "program")
    q.add_argument("--no-async", action="store_true",
                   help="batched mode: disable the double-buffered chunk "
                        "executor (prefetch thread overlapping host "
                        "staging with device compute) and run the serial "
                        "staging loop instead; results are bit-identical "
                        "either way")
    q.add_argument("--mesh", type=int, nargs=2, default=None,
                   metavar=("DATA", "CHAN"),
                   help="batched mode: mesh shape (data x chan "
                        "parallelism; CHAN>1 shards the sspec FFT's "
                        "channel axis)")
    q.add_argument("--bucket", action="store_true",
                   help="batched mode: canonicalise each shape bucket "
                        "onto the closed batch-ladder catalog "
                        "(pad to the nearest rung / chunk at the top "
                        "rung — only `warmup --catalog` signatures "
                        "execute; real-lane results byte-identical)")
    q.add_argument("--xprof", default=None, metavar="DIR",
                   help="batched mode: bracket the pipeline's measure "
                        "window in jax.profiler.trace — a TensorBoard/"
                        "XProf-loadable device timeline lands in DIR, "
                        "with pipeline.stage/step/gather annotations "
                        "naming the regions")
    _add_perf_policy_flags(q)
    _add_synth_flags(q)
    _add_infer_flags(q)
    _add_search_flags(q)
    q.set_defaults(fn=cmd_process)

    q = sub.add_parser(
        "warmup",
        help="pre-compile the batched step set for a template + config "
             "(persistent compile cache + AOT export), so a later "
             "`process --batched` run re-traces nothing")
    q.add_argument("files", nargs="*",
                   help="template psrflux file(s): the survey's inputs "
                        "or one representative epoch per observing "
                        "setup (omit with --synthetic)")
    q.add_argument("--batch", type=int, default=None,
                   help="planned survey batch size per shape bucket "
                        "(default: the number of template files in the "
                        "bucket) — pass the production size to warm up "
                        "from a few template files")
    q.add_argument("--lamsteps", action="store_true")
    q.add_argument("--no-arc", action="store_true")
    q.add_argument("--no-scint", action="store_true")
    q.add_argument("--scint-2d", action="store_true")
    q.add_argument("--arc-asymm", action="store_true")
    q.add_argument("--arc-method", default="norm_sspec",
                   choices=["norm_sspec", "gridmax", "thetatheta"])
    q.add_argument("--arc-bracket", type=float, nargs=2, default=None,
                   metavar=("LO", "HI"))
    q.add_argument("--arc-stack", action="store_true")
    q.add_argument("--clean", action="store_true",
                   help="mirror process --clean (cleaning changes epoch "
                        "shapes, so the warmed signatures must match)")
    q.add_argument("--chunk-epochs", type=int, default=None,
                   help="mirror the survey's --chunk-epochs (the uneven "
                        "trailing-chunk signature is warmed too)")
    q.add_argument("--pad-chunks", action="store_true",
                   help="mirror the survey's --pad-chunks (one compiled "
                        "program per bucket)")
    q.add_argument("--no-async", action="store_true",
                   help="mirror the survey's --no-async (input donation "
                        "differs, which is part of the cache key)")
    q.add_argument("--mesh", type=int, nargs=2, default=None,
                   metavar=("DATA", "CHAN"))
    q.add_argument("--no-mesh", action="store_true", dest="no_mesh",
                   help="warm the MESHLESS step signatures (what a "
                        "`serve` worker without --mesh executes); the "
                        "default mirrors `process --batched` (full "
                        "local mesh)")
    q.add_argument("--arc-numsteps", type=int, default=None,
                   help="mirror a consumer's eta-grid size override "
                        "(enters the compiled signature, so the warmed "
                        "programs must match)")
    q.add_argument("--lm-steps", type=int, default=None,
                   help="mirror a consumer's LM iteration budget "
                        "(enters the compiled signature)")
    q.add_argument("--force", action="store_true",
                   help="re-export even when an artifact already exists")
    q.add_argument("--catalog", action="store_true",
                   help="pre-compile the CLOSED shape-bucket catalog "
                        "(every batch-ladder rung per template setup, "
                        "scintools_tpu.buckets) instead of this "
                        "survey's raw sizes — a worker warmed this way "
                        "serves ANY epoch count with jit_cache_miss=0 "
                        "when callers canonicalise (--bucket / serve); "
                        "--batch overrides the ladder top "
                        "(SCINT_BUCKET_TOP, default 64)")
    _add_perf_policy_flags(q)
    _add_synth_flags(q)
    _add_search_flags(q)
    q.set_defaults(fn=cmd_warmup)

    q = sub.add_parser(
        "serve",
        help="run a resident survey worker: claim queued epochs, batch "
             "them onto warm compiled steps, write idempotent results "
             "(the queue dir is the API — see submit/status/drain)")
    q.add_argument("queue", help="queue directory (created if absent)")
    q.add_argument("--batch", type=int, default=8,
                   help="dynamic batch size = the compiled step's padded "
                        "batch shape (warm it with `warmup --batch N`)")
    q.add_argument("--max-wait", type=float, default=2.0,
                   help="max seconds a partial batch waits for more "
                        "compatible epochs before flushing padded")
    q.add_argument("--lease", type=float, default=60.0,
                   help="job lease seconds: a SIGKILLed worker's claims "
                        "are requeued after this expires")
    q.add_argument("--poll", type=float, default=0.2,
                   help="idle queue poll interval (seconds)")
    q.add_argument("--max-retries", type=int, default=3,
                   help="retries (with exponential backoff) before a "
                        "job is poisoned to failed/")
    q.add_argument("--max-batches", type=int, default=None,
                   help="exit after this many executed batches")
    q.add_argument("--idle-exit", type=float, default=None,
                   help="exit after this many seconds with no work")
    q.add_argument("--ignore-drain", action="store_true",
                   help="keep serving even when a drain is requested")
    q.add_argument("--results", default=None,
                   help="export the results store to this CSV on exit")
    q.add_argument("--full-csv", action="store_true",
                   help="with --results: export EVERY store column")
    q.add_argument("--no-async", action="store_true",
                   help="disable the async chunk executor (as process)")
    q.add_argument("--mesh", type=int, nargs=2, default=None,
                   metavar=("DATA", "CHAN"),
                   help="device mesh (as process --batched); --batch "
                        "must divide by DATA")
    q.add_argument("--bucket", action="store_true",
                   help="pad partial flushes to the nearest batch-"
                        "ladder rung (warmup --catalog signatures) "
                        "instead of the full --batch: less pad waste, "
                        "same byte-identical results, still zero "
                        "tracing on a warmed worker")
    q.add_argument("--heartbeat", type=float, default=10.0,
                   metavar="SECONDS",
                   help="liveness/telemetry snapshot interval: the "
                        "worker atomically rewrites heartbeat/"
                        "<worker>.json in the queue dir every N "
                        "seconds (`fleet status` merges them; 0 "
                        "disables)")
    q.add_argument("--shards", type=int, default=None,
                   help="queued-namespace shard count for a FRESH "
                        "queue dir (default 8, or SCINT_QUEUE_SHARDS); "
                        "an existing queue's persisted control/shards "
                        "value always wins")
    q.add_argument("--worker-id", default=None, dest="worker_id",
                   help="explicit worker id (default <host>:<pid>) — "
                        "the pool controller names its workers so "
                        "heartbeats, hints and per-worker drain "
                        "markers line up")
    q.add_argument("--lane-budgets", default=None, dest="lane_budgets",
                   metavar="LANE=N,...",
                   help="weighted-fair claim budgets per QoS lane, "
                        "e.g. interactive=3,bulk=1 (the default): per "
                        "claim cycle up to N candidates are taken "
                        "from each lane, so bulk campaigns cannot "
                        "starve interactive submits")
    q.add_argument("--xprof", default=None, metavar="DIR",
                   help="bracket the whole serving session in "
                        "jax.profiler.trace (device timeline to DIR; "
                        "serve.batch annotations name each executed "
                        "batch) — for profiling a worker under load")
    q.set_defaults(fn=cmd_serve)

    q = sub.add_parser(
        "submit",
        help="submit epoch files to a serve queue (idempotent per file "
             "content + estimator options)")
    q.add_argument("queue", help="queue directory (created if absent)")
    q.add_argument("files", nargs="*",
                   help="epoch files (omit with --synthetic)")
    q.add_argument("--lamsteps", action="store_true")
    q.add_argument("--no-arc", action="store_true")
    q.add_argument("--no-scint", action="store_true")
    q.add_argument("--scint-2d", action="store_true")
    q.add_argument("--arc-asymm", action="store_true")
    q.add_argument("--arc-method", default="norm_sspec",
                   choices=["norm_sspec", "gridmax", "thetatheta"])
    q.add_argument("--arc-bracket", type=float, nargs=2, default=None,
                   metavar=("LO", "HI"))
    q.add_argument("--clean", action="store_true",
                   help="RFI/gain cleaning before the fits (enters the "
                        "job identity, as process --clean enters the "
                        "resume key)")
    q.add_argument("--arc-numsteps", type=int, default=None,
                   help="override the eta-grid size (advanced; enters "
                        "the job identity)")
    q.add_argument("--lm-steps", type=int, default=None,
                   help="override the LM iteration budget (advanced; "
                        "enters the job identity)")
    q.add_argument("--wait", type=float, default=None,
                   help="block until the submitted jobs are terminal "
                        "(or this many seconds pass)")
    q.add_argument("--compact", action="store_true",
                   help="submit a results-plane compaction job instead "
                        "of epochs: the worker merges small segment "
                        "files into one (docs/performance.md)")
    q.add_argument("--stream", default=None, metavar="FEED",
                   help="register a live append-mode feed directory "
                        "(stream job kind): the worker re-fits the "
                        "last --stream-window samples every "
                        "--stream-hop new ones and publishes "
                        "versioned rows per tick (docs/streaming.md)")
    q.add_argument("--stream-window", type=int, default=None,
                   dest="stream_window", metavar="W",
                   help="sliding-window length in time samples "
                        "(default 256; enters the job identity — the "
                        "ONE compiled signature every tick executes)")
    q.add_argument("--stream-hop", type=int, default=None,
                   dest="stream_hop", metavar="H",
                   help="minimum new samples between ticks (default "
                        "window/4; enters the job identity)")
    q.add_argument("--stream-incremental", action="store_true",
                   dest="stream_incremental",
                   help="O(hop) incremental ticks: sliding-window "
                        "sspec/ACF updates + a warm-started fitter, "
                        "with periodic exact resync to the full path "
                        "(docs/streaming.md; enters the job identity)")
    q.add_argument("--stream-resync", type=int, default=None,
                   dest="stream_resync", metavar="N",
                   help="full-recompute resync cadence for "
                        "--stream-incremental ticks (default 16; "
                        "bounds sliding-update float drift)")
    q.add_argument("--lane", default=None,
                   choices=["interactive", "bulk"],
                   help="QoS lane (scheduling priority, never job "
                        "identity): file submits default to "
                        "interactive, --synthetic campaigns to bulk; "
                        "claim order is weighted-fair over the lanes "
                        "(serve --lane-budgets)")
    _add_perf_policy_flags(q)
    _add_synth_flags(q)
    _add_infer_flags(q)
    _add_search_flags(q)
    q.set_defaults(fn=cmd_submit)

    q = sub.add_parser(
        "pool",
        help="run the fleet pool controller: autoscale serve workers "
             "around the heartbeat backpressure signal, replace stale "
             "ones, and publish warm/memory-affinity claim hints")
    q.add_argument("queue", help="queue directory (created if absent)")
    q.add_argument("--min", type=int, default=1,
                   help="minimum resident workers (refilled "
                        "immediately, no cooldown)")
    q.add_argument("--max", type=int, default=4,
                   help="maximum workers")
    q.add_argument("--high", type=float, default=0.5,
                   help="scale-up backpressure threshold (0.5 = the "
                        "backlog equals one 60 s horizon of drain)")
    q.add_argument("--low", type=float, default=0.1,
                   help="scale-down backpressure threshold")
    q.add_argument("--cooldown", type=float, default=15.0,
                   help="seconds between scale decisions")
    q.add_argument("--poll", type=float, default=1.0,
                   help="control-round interval (seconds)")
    q.add_argument("--rounds", type=int, default=None,
                   help="exit after this many control rounds "
                        "(default: run until drained/interrupted)")
    # worker pass-through knobs (each spawned `serve` subprocess)
    q.add_argument("--batch", type=int, default=8,
                   help="worker dynamic batch size (serve --batch)")
    q.add_argument("--max-wait", type=float, default=2.0,
                   help="worker partial-batch wait (serve --max-wait)")
    q.add_argument("--lease", type=float, default=60.0,
                   help="worker job lease seconds (serve --lease)")
    q.add_argument("--heartbeat", type=float, default=2.0,
                   help="worker heartbeat interval — the controller's "
                        "whole signal set rides on it, so pool "
                        "workers default to 2 s (serve --heartbeat)")
    q.add_argument("--bucket", action="store_true",
                   help="workers pad partial flushes to batch-ladder "
                        "rungs (serve --bucket)")
    q.add_argument("--lane-budgets", default=None, dest="lane_budgets",
                   metavar="LANE=N,...",
                   help="workers' weighted-fair claim budgets "
                        "(serve --lane-budgets)")
    q.set_defaults(fn=cmd_pool)

    q = sub.add_parser("status",
                       help="print a serve queue's state as one JSON "
                            "line")
    q.add_argument("queue")
    q.set_defaults(fn=cmd_queue_status)

    q = sub.add_parser(
        "drain",
        help="ask the resident worker(s) to finish the queue and exit")
    q.add_argument("queue")
    q.add_argument("--timeout", type=float, default=None,
                   help="wait up to this many seconds for the queue to "
                        "empty (omit to just set the marker)")
    q.add_argument("--results", default=None,
                   help="export the results store to this CSV")
    q.add_argument("--full-csv", action="store_true",
                   help="with --results: export EVERY store column")
    q.add_argument("--latest-only", action="store_true",
                   dest="latest_only",
                   help="with --results: collapse each versioned "
                        "stream series to its newest row (final "
                        "values per live feed, not the whole tracked "
                        "time series)")
    q.set_defaults(fn=cmd_drain)

    q = sub.add_parser("sort", help="triage files into good/bad lists")
    q.add_argument("files", nargs="+")
    q.add_argument("--outdir")
    q.add_argument("--min-nsub", type=int, default=10)
    q.add_argument("--min-nchan", type=int, default=50)
    q.add_argument("--min-freq", type=float, default=0)
    q.add_argument("--max-freq", type=float, default=5000)
    q.add_argument("--verbose", action="store_true")
    q.set_defaults(fn=cmd_sort)

    q = sub.add_parser("sim", help="simulate a dynspec -> psrflux file")
    q.add_argument("--out", required=True)
    q.add_argument("--mb2", type=float, default=2)
    q.add_argument("--rf", type=float, default=1)
    q.add_argument("--ds", type=float, default=0.01)
    q.add_argument("--alpha", type=float, default=5 / 3)
    q.add_argument("--ar", type=float, default=1)
    q.add_argument("--psi", type=float, default=0)
    q.add_argument("--inner", type=float, default=0.001)
    q.add_argument("--ns", type=int, default=256)
    q.add_argument("--nf", type=int, default=256)
    q.add_argument("--dlam", type=float, default=0.25)
    q.add_argument("--seed", type=int, default=None)
    q.add_argument("--ensemble", type=int, default=1,
                   help="write N consecutively-seeded epochs "
                        "(<out-stem>_KKKK.<ext>) instead of one file")
    q.add_argument("--freq", type=float, default=1400.0)
    q.add_argument("--dt", type=float, default=8.0)
    q.add_argument("--backend", default="numpy",
                   choices=["numpy", "jax"])
    q.set_defaults(fn=cmd_sim)

    q = sub.add_parser(
        "curvature",
        help="fit screen parameters to a survey's curvature time series")
    q.add_argument("results",
                   help="results CSV from `process --lamsteps` (needs "
                        "the betaeta column)")
    q.add_argument("--par", required=True,
                   help="tempo2 .par file with RAJ/DECJ (+ orbit keys "
                        "for binaries)")
    q.add_argument("--fit", nargs="+", default=["s", "vism_psi"],
                   choices=["s", "d", "psi", "vism_psi", "vism_ra",
                            "vism_dec"],
                   help="screen keys to fit")
    q.add_argument("--start", nargs="*", default=None, metavar="KEY=VAL",
                   help="starting values / fixed screen parameters")
    q.add_argument("--plot", default=None,
                   help="write a data-vs-model PNG here")
    q.add_argument("--backend", default="numpy",
                   choices=["numpy", "jax"])
    q.set_defaults(fn=cmd_curvature)

    q = sub.add_parser(
        "wavefield",
        help="retrieve the complex wavefield (theta-theta holography)")
    q.add_argument("files", nargs="+", help="psrflux dynspec files")
    q.add_argument("--eta", type=float, default=None,
                   help="arc curvature (us/mHz^2); omit to fit it")
    q.add_argument("--etamin", type=float, default=1e-4,
                   help="curvature-fit bracket (used when --eta omitted)")
    q.add_argument("--etamax", type=float, default=100.0)
    q.add_argument("--numsteps", type=int, default=128,
                   help="curvature-sweep points")
    q.add_argument("--chunk", type=int, default=64,
                   help="chunk size (both axes)")
    q.add_argument("--out", default=None,
                   help="output .npz (single input only; default "
                        "<file>.wavefield.npz)")
    q.add_argument("--plots", action="store_true",
                   help="also write wavefield + field-sspec PNGs")
    q.add_argument("--conc-weight", type=float, default=0.0,
                   help="blend-weight exponent on per-chunk eigenmode "
                        "concentration (0 = uniform blend)")
    q.add_argument("--refine", type=int, default=10,
                   help="alternating-projection iterations per chunk "
                        "after the eigen seed (0 = pure eigenvector "
                        "retrieval)")
    q.add_argument("--refine-global", default="auto",
                   type=lambda v: v if v == "auto" else int(v),
                   help="global arc-support Gerchberg-Saxton iterations "
                        "on the stitched field: 'auto' (default) "
                        "refines per epoch iff the measured intensity "
                        "corr is < 0.80 (picks the better branch in "
                        "every cell of the docs/wavefield.md regime "
                        "map); 0 = never, N = always N iterations")
    q.add_argument("--backend", default="numpy",
                   choices=["numpy", "jax", "auto"])
    q.set_defaults(fn=cmd_wavefield)

    q = sub.add_parser("bench", help="run the headline benchmark")
    q.add_argument("--xprof", default=None, metavar="DIR",
                   help="bracket the bench measure window in "
                        "jax.profiler.trace (sets SCINT_BENCH_XPROF; "
                        "the device timeline lands in DIR)")
    q.set_defaults(fn=cmd_bench)

    q = sub.add_parser("trace",
                       help="inspect JSONL traces written by --trace")
    tsub = q.add_subparsers(dest="trace_command", required=True)
    r = tsub.add_parser("report",
                        help="per-stage span table (count/total/p50/p95) "
                             "+ counters, merged over trace file(s)")
    r.add_argument("tracefile", nargs="+",
                   help="JSONL trace(s) written by --trace; literal "
                        "paths or glob patterns, merged into one "
                        "report (torn lines warn + skip)")
    r.add_argument("--fleet", action="store_true",
                   help="treat each argument as a fleet directory "
                        "(worker heartbeats + traces + crash flights) "
                        "and print the merged per-worker rollup with "
                        "the backpressure scalar")
    r.add_argument("--since", default=None, metavar="TS",
                   help="event-time filter: keep only records stamped "
                        "at/after TS (unix seconds, or an ISO date/"
                        "datetime like 2026-08-04T12:00) — multi-day "
                        "merged JSONL reports one window at a time")
    r.add_argument("--last", default=None, metavar="DUR",
                   help="event-time filter: keep only records within "
                        "the trailing DUR (N[s|m|h|d], e.g. 2h) of "
                        "the NEWEST stamped record — event time, not "
                        "wall clock, so old traces still filter "
                        "meaningfully")
    r.set_defaults(fn=cmd_trace_report)

    q = sub.add_parser(
        "fleet",
        help="fleet-level telemetry over a serve queue directory")
    fsub = q.add_subparsers(dest="fleet_command", required=True)
    r = fsub.add_parser(
        "status",
        help="merge worker heartbeats + traces into per-worker and "
             "aggregate tables with the backpressure scalar "
             "(docs/observability.md, fleet section)")
    r.add_argument("queue", help="serve queue dir (or any dir holding "
                                 "heartbeat/*.json)")
    r.add_argument("--json", action="store_true",
                   help="machine-readable rollup (the admission-"
                        "control input) instead of the table")
    r.set_defaults(fn=cmd_fleet_status)

    q = sub.add_parser(
        "fsck",
        help="audit a serve queue directory's on-disk invariants "
             "(orphaned tmp/open files, torn segments, misplaced or "
             "terminal-twin queued records, expired leases, stale "
             "drain markers, stream cursor skew — docs/reliability.md)")
    q.add_argument("queue", help="serve queue dir to audit")
    q.add_argument("--repair", action="store_true",
                   help="apply the proven recovery actions (the same "
                        "code paths crash recovery runs); default is "
                        "a report-only dry run")
    q.add_argument("--json", action="store_true",
                   help="machine-readable report instead of the table")
    q.set_defaults(fn=cmd_fsck)

    q = sub.add_parser(
        "alerts",
        help="durable SLO alerts of a serve queue directory: list the "
             "newest-wins rows, acknowledge one, or show a row's "
             "transition history (docs/slo.md)")
    q.add_argument("queue", help="serve queue dir holding results/ "
                                 "alert rows and slo.json")
    q.add_argument("--ack", default=None, metavar="SLO",
                   help="acknowledge the named SLO's alert (a durable "
                        "newest-wins write; the row keeps firing but "
                        "is marked acked in every readout)")
    q.add_argument("--history", default=None, metavar="SLO",
                   help="print the named alert's retained transition "
                        "history ([ts, state] pairs, newest last)")
    q.add_argument("--json", action="store_true",
                   help="machine-readable rows instead of the table")
    q.set_defaults(fn=cmd_alerts)
    return p


def main(argv: list[str] | None = None) -> int:
    from . import faults
    from .backend import honor_platform_env

    honor_platform_env()
    # arm any SCINT_FAULTS-requested chaos faults (no-op when unset):
    # subprocess chaos drives inject through the environment
    faults.install_env()
    parser = build_parser()
    args, extra = parser.parse_known_args(argv)
    if extra:
        files = getattr(args, "files", None)
        if files is not None and all(not t.startswith("-")
                                     for t in extra):
            # argparse's zero-width nargs="*" match consumes the files
            # slot before interspersed flags (`submit q --lamsteps f1
            # f2` left f1/f2 "unrecognized" once --synthetic made files
            # optional): fold trailing non-flag tokens back into it —
            # exactly the nargs="+" interleaving that always worked
            args.files = list(files) + extra
        else:
            parser.error("unrecognized arguments: " + " ".join(extra))
    if args.trace:
        try:
            obs.enable(jsonl=args.trace)
        except OSError as e:
            print(f"--trace {args.trace}: cannot open ({e})",
                  file=sys.stderr)
            return 1
    try:
        return args.fn(args)
    finally:
        if args.trace:
            obs.disable()  # flush counters + close the JSONL sink


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
