"""Channel-sharded secondary spectrum for ONE dynspec too large for a
single device — the load-bearing "sharded FFT" component (SURVEY.md §2.7,
round-3 verdict item 4).

The batched pipeline chan-shards its FFTs through the GSPMD partitioner
(parallel/driver.py); this module is the EXPLICIT program for the single
giant-spectrum case, written as a classic distributed 2-D FFT so every
byte of per-device working set is accounted for:

    rows (channels) sharded over the mesh
      1. global mean subtraction           — psum
      2. separable edge window             — shard-local (host 1-D vectors)
      3. second mean subtraction           — psum (reference quirk,
                                             dynspec.py:1251,1280)
      4. 2x2 prewhitening difference       — one-row halo via ppermute
      5. Doppler-axis fftshift             — pre-modulation by (-1)^t
                                             (no post-FFT block permute)
      6. FFT along time                    — shard-local, rows sharded
      7. distributed transpose             — all_to_all (ICI)
      8. FFT along delay                   — shard-local, columns sharded
      9. |.|^2, crop positive delays,
         postdark sin^2, 10 log10          — shard-local (postdark built
                                             per-shard from axis_index)

Per-device peak working set is ~(2 complex64 copies of the padded grid)/P
versus ~2 full copies (plus FFT temporaries) on one device: at a 32k x 32k
padded grid (input ~16k x 16k) the single-device working set is ~16-32 GB
— beyond one v4/v5e chip's HBM once the batched pipeline's buffers are
resident — while 8-way sharding holds ~2-4 GB/device.  The dryrun
(__graft_entry__.dryrun_multichip) validates this program against the
host-tiled reference at a driver-sized grid, and
tests/test_parallel.py asserts equality at several sizes (the genuinely
HBM-scale grid is env-gated: SCINT_BIG_FFT=1).

Output matches ops.sspec.sspec exactly (same quirks, same axis ordering):
[nrfft/2, ncfft] in dB, Doppler axis shifted.
"""

from __future__ import annotations

import functools

import numpy as np

from ..ops.sspec import next_pow2_fft_lens
from ..ops.windows import split_window
from . import mesh as mesh_mod

__all__ = ["sspec_sharded", "sspec_host_tiled"]

_ROWS = "rows"


def _flat_row_mesh(mesh):
    """1-D mesh over all of ``mesh``'s devices (power-of-two count
    required by the block-aligned transpose/shift)."""
    devs = list(np.asarray(mesh.devices).ravel())
    P = len(devs)
    if P & (P - 1):
        # largest power-of-two subset: the transpose needs block alignment
        P = 1 << (P.bit_length() - 1)
        devs = devs[:P]
    return mesh_mod.make_mesh(shape=(P,), axis_names=(_ROWS,),
                              devices=devs), P


@functools.lru_cache(maxsize=4)
def _build(P: int, nf: int, nt: int, prewhite: bool, window,
           window_frac: float, db: bool, mesh):
    # Mesh is value-hashable, so it keys the lru_cache directly
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as Pt

    nrfft, ncfft = next_pow2_fft_lens(nf, nt)
    if nrfft % P or ncfft % P:
        raise ValueError(
            f"padded grid {nrfft}x{ncfft} (from dynspec {nf}x{nt}) is not "
            f"divisible by the {P}-device row mesh; use a spectrum with "
            f"at least {P // 2 + 1} rows and columns or a smaller mesh")
    br = nrfft // P          # row block per device (input padded to nrfft)
    bc = ncfft // P          # column block after the transpose

    # host-built 1-D constants (static shapes -> folded into the trace)
    if window is not None:
        tw = split_window(nt, window, window_frac).astype(np.float32)
        fw = split_window(nf, window, window_frac).astype(np.float32)
    else:
        tw = np.ones(nt, np.float32)
        fw = np.ones(nf, np.float32)
    fw_pad = np.zeros(nrfft, np.float32)
    fw_pad[:nf] = fw
    # Doppler fftshift as pre-modulation along the (unsharded) time axis
    mod = ((-1.0) ** np.arange(ncfft)).astype(np.float32)
    inv_n = np.float32(1.0 / (nf * nt))

    def block(d, fwb):
        # d: [br, nt] local rows; fwb: [br] local slice of fw_pad
        idx = jax.lax.axis_index(_ROWS)
        grow = idx * br + jnp.arange(br)          # global row indices
        valid = (grow < nf).astype(d.dtype)[:, None]

        m1 = jax.lax.psum(jnp.sum(d), _ROWS) * inv_n
        d = (d - m1) * valid
        d = d * tw[None, :] * fwb[:, None]
        m2 = jax.lax.psum(jnp.sum(d), _ROWS) * inv_n
        d = (d - m2) * valid

        if prewhite:
            # halo: first local row of device i+1 (garbage wrap-around at
            # the last device is masked: rows >= nf-1 are invalid)
            halo = jax.lax.ppermute(
                d[:1], _ROWS, [((i + 1) % P, i) for i in range(P)])
            dn = jnp.concatenate([d[1:], halo], axis=0)  # row r+1
            pw = (dn[:, 1:] - dn[:, :-1] - d[:, 1:] + d[:, :-1])
            pw = pw * (grow < nf - 1).astype(d.dtype)[:, None]
            pw = jnp.pad(pw, ((0, 0), (0, ncfft - (nt - 1))))
        else:
            pw = jnp.pad(d, ((0, 0), (0, ncfft - nt)))

        f1 = jnp.fft.fft(pw * mod[None, :], axis=-1)     # [br, ncfft]
        # distributed transpose: split time into P blocks, gather all rows
        f1t = jax.lax.all_to_all(f1, _ROWS, split_axis=1, concat_axis=0,
                                 tiled=True)             # [nrfft, bc]
        f2 = jnp.fft.fft(f1t, axis=0)                    # [nrfft, bc]
        sec = jnp.real(f2) ** 2 + jnp.imag(f2) ** 2
        sec = sec[: nrfft // 2]                          # positive delays

        if prewhite:
            # postdark for THIS column shard, in the already-shifted
            # Doppler order (ops.sspec._postdark: fd = col - ncfft/2).
            # NB the singular fixes apply to the full 2-D ROW 0 and
            # COLUMN ncfft/2 (pd forced to exactly 1 there), not to the
            # 1-D factors
            gcol = idx * bc + jnp.arange(bc)
            v1 = jnp.sin(jnp.pi / ncfft * (gcol - ncfft // 2)) ** 2
            v2 = jnp.sin(jnp.pi / nrfft * jnp.arange(nrfft // 2)) ** 2
            pd = v2[:, None] * v1[None, :]
            pd = jnp.where((gcol == ncfft // 2)[None, :], 1.0, pd)
            pd = pd.at[0, :].set(1.0)
            sec = sec / pd
        if db:
            sec = 10.0 * jnp.log10(sec)
        return sec

    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    fn = shard_map(block, mesh=mesh,
                   in_specs=(Pt(_ROWS, None), Pt(_ROWS)),
                   out_specs=Pt(None, _ROWS))
    jfn = jax.jit(fn, in_shardings=(NamedSharding(mesh, Pt(_ROWS, None)),
                                    NamedSharding(mesh, Pt(_ROWS))))
    return jfn, fw_pad, nrfft, ncfft


def sspec_sharded(dyn, mesh, prewhite: bool = True,
                  window: str | None = "blackman",
                  window_frac: float = 0.1, db: bool = True):
    """Secondary spectrum of ONE [nf, nt] dynspec with the padded FFT grid
    sharded over ``mesh``'s devices (rows before the transpose, Doppler
    columns after).  Returns a global jax array [nrfft/2, ncfft] whose
    shards live one per device; numerics match ``ops.sspec.sspec`` (f32).
    """
    import jax

    dyn = np.asarray(dyn, dtype=np.float32)
    if dyn.ndim != 2:
        raise ValueError(f"sspec_sharded takes one [nf, nt] dynspec, "
                         f"got shape {dyn.shape}")
    if dyn.shape[0] < 2 or dyn.shape[1] < 2:
        # same contract as ops.sspec.sspec — prewhitening differences
        # both axes, and a sub-2 axis would silently mask to all zeros
        raise ValueError(f"secondary spectrum needs at least a 2x2 "
                         f"dynspec, got {dyn.shape}")
    nf, nt = dyn.shape
    flat, P = _flat_row_mesh(mesh)
    jfn, fw_pad, nrfft, _ = _build(P, nf, nt, bool(prewhite), window,
                                   float(window_frac), bool(db), flat)
    # pad rows host-side so every device holds an equal block; padded rows
    # are masked inside the program and land in the FFT's zero padding
    dyn_pad = np.zeros((nrfft, nt), np.float32)
    dyn_pad[:nf] = dyn
    return jfn(dyn_pad, fw_pad)


def sspec_host_tiled(dyn, prewhite: bool = True,
                     window: str | None = "blackman",
                     window_frac: float = 0.1, db: bool = True,
                     tile: int = 1024):
    """Host reference for :func:`sspec_sharded`: the same secondary
    spectrum computed with numpy in row/column TILES, so the host never
    materialises more than one padded complex copy plus a tile — the
    "host-tiled computation" the sharded result is asserted against
    (independent of both ops.sspec paths; float64 per tile).
    """
    from ..ops.windows import apply_2d_window

    dyn = np.asarray(dyn, dtype=np.float64)  # host-f64: host-tiled oracle
    nf, nt = dyn.shape
    nrfft, ncfft = next_pow2_fft_lens(nf, nt)
    d = dyn - dyn.mean()
    if window is not None:
        d = apply_2d_window(d, window, window_frac, backend="numpy")
    d = d - d.mean()
    if prewhite:
        pw = d[1:, 1:] - d[1:, :-1] - d[:-1, 1:] + d[:-1, :-1]
    else:
        pw = d

    # FFT along time in row tiles into the single full buffer
    buf = np.zeros((nrfft, ncfft), np.complex128)  # host-f64: host-tiled oracle
    for r0 in range(0, pw.shape[0], tile):
        blk = pw[r0:r0 + tile]
        buf[r0:r0 + blk.shape[0]] = np.fft.fft(blk, n=ncfft, axis=1)
    # FFT along rows in column tiles, in place
    for c0 in range(0, ncfft, tile):
        buf[:, c0:c0 + tile] = np.fft.fft(buf[:, c0:c0 + tile], axis=0)

    sec = (buf.real ** 2 + buf.imag ** 2)[: nrfft // 2]
    del buf
    sec = np.fft.fftshift(sec, axes=1)
    if prewhite:
        from ..ops.sspec import _postdark

        sec = sec / _postdark(nrfft, ncfft)
    if db:
        with np.errstate(divide="ignore"):
            sec = 10 * np.log10(sec)
    return sec
