"""Padded batching of heterogeneous observing epochs.

The reference processes epochs one at a time in a serial Python loop
(``sort_dyn``, dynspec.py:1615-1657; the notebook's epoch-summing loop).
The TPU pipeline instead wants one [B, nf, nt] array per step.  Real epochs
have heterogeneous shapes, so batching is pad-and-mask (SURVEY.md hard part
(c)):

* epochs are grouped into shape buckets (`bucket_by_shape`) — same
  observing setup -> same shape -> zero padding waste, one compile;
* within a bucket (or when forcing a single shape) `pad_batch` pads each
  dyn with its *own mean* — after the sspec kernel's mean subtraction
  (ops/sspec.py) padded pixels are ~0, so they add no FFT power, matching
  the reference's `refill` policy of filling gaps with the mean
  (dynspec.py:1186-1187);
* a `BatchMask` records what was real, and is carried through vmapped fits
  so invalid lanes are dropped at gather time instead of raising (the
  quarantine pattern of sort_dyn, made SPMD).
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Any, Sequence

import numpy as np

from ..data import DynspecData, stack_batch


@dataclasses.dataclass(frozen=True)
class BatchMask:
    """Validity masks for a padded batch (all numpy, host-side)."""

    epoch: Any  # [B] bool — False for pad-epochs added for divisibility
    freq: Any   # [B, nf] bool — True where the channel is real
    time: Any   # [B, nt] bool — True where the subint is real

    @property
    def n_valid(self) -> int:
        return int(np.sum(self.epoch))


def bucket_by_shape(epochs: Sequence[DynspecData]):
    """Group epoch indices by dyn shape: {(nf, nt): [indices]}.

    Shape equality alone does NOT imply the epochs can share one pipeline
    (same shape, different band → different df/fc/λ-grid); the driver
    buckets on full axis identity (parallel.driver.run_pipeline)."""
    buckets: dict[tuple, list[int]] = defaultdict(list)
    for i, d in enumerate(epochs):
        buckets[(d.nchan, d.nsub)].append(i)
    return dict(buckets)


def _pad_axis(x: np.ndarray, n: int) -> np.ndarray:
    """Extend a 1-D coordinate axis to length n, continuing its grid."""
    if len(x) >= n:
        return x[:n]
    step = x[1] - x[0] if len(x) > 1 else 1.0
    extra = x[-1] + step * np.arange(1, n - len(x) + 1)
    return np.concatenate([x, extra])


def pad_epoch(d: DynspecData, nchan: int, nsub: int,
              fill: str = "mean") -> tuple[DynspecData, np.ndarray, np.ndarray]:
    """Pad one epoch to [nchan, nsub]; returns (padded, freq_mask, time_mask).

    fill='mean' pads with the epoch mean (zero power after mean-subtract);
    fill='zero' pads with 0 (matches the reference's time-concat gap fill,
    dynspec.py:76-84).
    """
    dyn = np.asarray(d.dyn, dtype=np.float64)  # host-f64: host staging (pre-policy)
    nf, nt = dyn.shape
    if nf > nchan or nt > nsub:
        raise ValueError(f"epoch {dyn.shape} larger than pad target "
                         f"({nchan}, {nsub}); crop first")
    value = float(np.mean(dyn)) if fill == "mean" else 0.0
    out = np.full((nchan, nsub), value, dtype=np.float64)  # host-f64: host staging (pre-policy)
    out[:nf, :nt] = dyn
    fmask = np.zeros(nchan, dtype=bool)
    fmask[:nf] = True
    tmask = np.zeros(nsub, dtype=bool)
    tmask[:nt] = True
    padded = d.replace(dyn=out, freqs=_pad_axis(np.asarray(d.freqs), nchan),
                       times=_pad_axis(np.asarray(d.times), nsub))
    return padded, fmask, tmask


def pad_batch(epochs: Sequence[DynspecData], nchan: int | None = None,
              nsub: int | None = None, batch_multiple: int = 1,
              fill: str = "mean") -> tuple[DynspecData, BatchMask]:
    """Pad epochs to a common shape, stack, and round B up to a multiple of
    ``batch_multiple`` (the mesh's data-axis size) with mask-invalid copies
    of the last epoch."""
    if not epochs:
        raise ValueError("empty batch")
    nchan = max(d.nchan for d in epochs) if nchan is None else nchan
    nsub = max(d.nsub for d in epochs) if nsub is None else nsub
    padded, fmasks, tmasks, valid = [], [], [], []
    for d in epochs:
        p, fm, tm = pad_epoch(d, nchan, nsub, fill=fill)
        padded.append(p)
        fmasks.append(fm)
        tmasks.append(tm)
        valid.append(True)
    while len(padded) % batch_multiple:
        padded.append(padded[-1])
        fmasks.append(fmasks[-1])
        tmasks.append(tmasks[-1])
        valid.append(False)
    batch = stack_batch(padded)
    mask = BatchMask(epoch=np.asarray(valid), freq=np.stack(fmasks),
                     time=np.stack(tmasks))
    return batch, mask
