from .batch import BatchMask, bucket_by_shape, pad_batch, pad_epoch  # noqa: F401
from .driver import (PipelineConfig, PipelineResult,  # noqa: F401
                     lambda_resample_matrix, make_pipeline, resolve_routes,
                     run_pipeline, survey_routes)
from .mesh import (CHAN_AXIS, DATA_AXIS, data_sharding, make_mesh,  # noqa: F401
                   replicated, shard_leading, sharded_mean)
from .schedule import execute_chunks  # noqa: F401
from .distributed import (initialize_multihost,  # noqa: F401
                          make_hybrid_mesh, survey_stats)
from .large_fft import sspec_host_tiled, sspec_sharded  # noqa: F401
