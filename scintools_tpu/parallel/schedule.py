"""Async double-buffered chunk execution for the batch driver.

``run_pipeline``'s chunk loop used to stage (slice/pad/transfer),
execute, and gather strictly serially, so the device idled during host
work even on warm surveys.  This module overlaps them the way GPU
pulsar pipelines hide host costs behind the FFT engine (arXiv:
1711.10855 §input pipeline): a producer thread stages chunk k+1 —
numpy slice + device placement — while the device executes chunk k.

Design constraints:

* **Bounded queue, depth 2.**  One staged chunk waiting + one being
  staged; HBM never holds more than ``depth`` staged inputs beyond the
  executing one.
* **Bit-identical to the sync path.**  Staging performs exactly the
  slice/``device_put`` the jit dispatch would do internally; execution
  order (and therefore every result) is unchanged — asserted by
  tests/test_schedule.py against the ``async_exec=False`` path.
* **Async dispatch preserved.**  ``step()`` returns un-fenced device
  futures; nothing here blocks on results (the driver's gather fences
  once at the end), so device-side chunk k+2 dispatch can overlap the
  k+1 transfer too.
* **Honest accounting.**  Producer staging runs under a
  ``pipeline.prefetch`` span; consumer time spent waiting on the queue
  accumulates in the ``prefetch_stall_s`` counter (seconds the device
  loop was starved by host staging).  Chunk 0's wait is excluded — it
  is unavoidable startup latency with nothing to overlap against, so
  a zero counter really does mean "staging fully hidden".

Errors on either side propagate: a staging exception re-raises in the
caller (with the producer stopped), and a step exception stops the
producer before it stages further chunks.
"""

from __future__ import annotations

import queue
import threading
import time

from .. import faults, obs

# one staged chunk in the queue + one being staged by the producer
DEFAULT_DEPTH = 2


class _StageError:
    """Sentinel carrying a producer-side exception to the consumer."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def execute_chunks(step, n_chunks: int, stage, *, async_exec: bool = True,
                   depth: int = DEFAULT_DEPTH, out: list | None = None
                   ) -> list:
    """Run ``[step(stage(k)) for k in range(n_chunks)]`` with chunk
    staging overlapped against device execution.

    ``stage(k)`` builds the device-ready input for chunk ``k`` (host
    slicing/padding + transfer); ``step(x)`` dispatches the compiled
    program (asynchronously — results are futures).  Results come back
    in chunk order.  ``async_exec=False`` (or a single chunk) runs the
    exact serial loop.

    ``out`` (optional) receives each chunk's result AS IT COMPLETES, in
    chunk order — on an exception the caller reads the completed prefix
    there, which is how the driver's OOM-adaptive backoff
    (driver._run_chunked_adaptive) replays only the unfinished chunks
    at a smaller size instead of the whole bucket.  The producer thread
    is always stopped and joined before the exception propagates, so a
    retry starts against a fresh prefetcher.
    """
    results = out if out is not None else []
    if not async_exec or n_chunks <= 1:
        for k in range(n_chunks):
            results.append(step(stage(k)))
        return results

    q: queue.Queue = queue.Queue(maxsize=max(int(depth) - 1, 1))
    stop = threading.Event()

    def produce():
        for k in range(n_chunks):
            if stop.is_set():
                return
            try:
                with obs.span("pipeline.prefetch", chunk=k):
                    # chaos site: a prefetch-thread death mid-survey
                    # must surface in the caller (docs/reliability.md)
                    faults.check("schedule.prefetch")
                    item = stage(k)
            except BaseException as e:  # fault-ok: carried to the
                #                         consumer as _StageError and
                #                         re-raised there
                item = _StageError(e)
            while not stop.is_set():
                try:
                    q.put((k, item), timeout=0.1)
                    break
                except queue.Full:
                    continue
            if isinstance(item, _StageError):
                return

    producer = threading.Thread(target=produce, name="scint-prefetch",
                                daemon=True)
    producer.start()
    stall_s = 0.0
    try:
        for n in range(n_chunks):
            t0 = time.perf_counter()
            k, item = q.get()
            if n > 0:
                # chunk 0's staging wait is unavoidable startup latency
                # (nothing to overlap against yet); only waits while
                # the device could have been busy count as stalls, so
                # prefetch_stall_s == 0 really means "fully hidden"
                stall_s += time.perf_counter() - t0
            if isinstance(item, _StageError):
                raise item.exc
            results.append(step(item))
    finally:
        stop.set()
        producer.join()
        if stall_s:
            obs.inc("prefetch_stall_s", round(stall_s, 6))
    return results
