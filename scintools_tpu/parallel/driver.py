"""Batched multi-epoch pipeline: the framework's "training step".

Reference analogue: the serial per-file loop of ``sort_dyn``
(dynspec.py:1615-1657) and the notebook's per-epoch workflow — here rebuilt
as ONE jit-compiled SPMD program over a [B, nf, nt] batch of dynamic
spectra (BASELINE config 4):

    dyn [B, nf, nt]
      ├─ ACF (Wiener–Khinchin fft2 pair, ops/acf.py)        → [B, 2nf, 2nt]
      │   └─ vmapped fixed-iteration LM tau/dnu fit          → ScintParams[B]
      ├─ (lamsteps) freq→lambda resample as ONE matmul       → [B, nlam, nt]
      │       (natural-cubic-spline weights precomputed host-side; the
      │        per-column interp1d loop of dynspec.py:1424-1426 becomes an
      │        MXU-friendly [nlam, nf] x [B, nf, nt] einsum)
      ├─ secondary spectrum (ops/sspec.py)                   → [B, nr, nc]
      │   └─ fixed-shape batched arc fitter (fit/arc_fit.py) → ArcFit[B]
      └─ results gathered host-side, invalid lanes dropped via BatchMask

All grid-dependent decisions (FFT lengths, eta grids, fold indices) are
made host-side from the static (freqs, times) template, so the device
program has static shapes and no data-dependent control flow.

With a mesh, the batch axis is sharded over ``data`` (DP: zero intra-step
communication) and optionally the channel axis over ``chan`` (SP analogue
for spectra too large for one device's HBM; XLA inserts ICI all-to-alls
around the sharded-axis FFT).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import numpy as np

from .. import faults, obs
from ..utils.timing import trace_annotation
from ..fit.arc_fit import make_arc_fitter
from ..fit.scint_fit import fit_scint_params_batch
from ..ops.acf import acf as acf_op
from ..ops.scale import lambda_grid
from ..ops.sspec import sspec as sspec_op, sspec_axes
from . import mesh as mesh_mod


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    """Static configuration of the batched step (hashable: jit cache key).

    Mirrors the kwargs of the reference's default_processing + fit calls
    (dynspec.py:188-198, 414-418, 928-934) as a typed config object
    (SURVEY.md §5 "config/flag system").
    """

    lamsteps: bool = True
    prewhite: bool = True
    window: str | None = "blackman"
    window_frac: float = 0.1
    fit_scint: bool = True
    fit_arc: bool = True
    fit_scint_2d: bool = False    # 2-D ACF fit incl. phase-gradient tilt
    alpha: float | None = 5 / 3       # None -> fit alpha too
    # fixed LM iteration count (the whole chain runs as one lax.scan).
    # Measured convergence on simulated epochs (mb2 2/8/20 mix, vs an
    # 80-100-step reference): the 1-D cuts fit is within 0.05 sigma by
    # 20 steps, the 2-D fit's measurable lanes within 1e-5 sigma (lanes
    # with tau >> tobs drift along a flat direction at ANY step count);
    # 40 bought nothing but latency — see tests/test_fit.py::
    # test_lm_steps_default_is_converged
    lm_steps: int = 20
    # Curvature estimator: "norm_sspec" / "gridmax" (the reference's two
    # power-profile methods, fit/arc_fit.py) or "thetatheta" (eigenvalue
    # concentration, fit/thetatheta.py — needs finite arc_constraint or
    # arc_brackets windows; arc_numsteps becomes the per-window sweep
    # size, where an untouched 2000 default is auto-replaced by 128)
    arc_method: str = "norm_sspec"
    arc_numsteps: int = 2000
    arc_ntheta: int = 129         # thetatheta only: theta-grid points
    arc_startbin: int = 3
    arc_cutmid: int = 3
    arc_nsmooth: int = 5
    arc_delmax: float | None = None
    arc_constraint: tuple = (0.0, np.inf)
    arc_asymm: bool = False       # per-arm eta_left/eta_right in ArcFit
    arc_brackets: tuple | None = None  # K (lo, hi) windows -> eta [B, K]
    # Campaign stacking (norm_sspec only; beyond the reference): ALSO
    # nanmean-stack the per-epoch normalised profiles across the batch
    # and measure once -> PipelineResult.arc_stacked (scalar ArcFit);
    # weak-arc S/N grows as sqrt(B).  Corrupted (NaN) epochs drop out
    # of the stack (nan-robust reductions); run_pipeline NaN-fills its
    # divisibility pad-lanes so they cannot bias the campaign.
    arc_stack: bool = False
    # Arc delay-scrunch strategy: 0 = full [B, R, n] gather, >0 = lax.scan
    # row blocks of that size (bounded HBM), "pallas" = fused VMEM kernel
    # (ops/resample_pallas; interpret mode off-TPU), -1 = auto: the
    # Pallas kernel on chip (round-4 A/B: 3.5x the scan at the bench
    # shape); on host CPU the scan with the largest block whose
    # [B_local, 4*block, numsteps] working set fits the 256 MiB cap
    # (round 5: the GEMM-reduction scan favours BIG blocks — one-block
    # 507 ms vs 16-row 615-696 ms at B=64 — so small batches get big
    # blocks and the bench batch keeps the 16-row floor)
    arc_scrunch_rows: int | str = -1
    # Arc measurement tail: "exact" (default) keeps the reference's
    # compacted-array semantics bit-for-bit (the parity contract —
    # dynspec.py:580-618,702-744); "fast" runs the same smooth/peak/
    # walk/parabola stages as masked reductions on the full grid.
    # Opt-in: eta agrees within the fit's own etaerr, not bit-exactly.
    arc_tail: str = "exact"
    # ACF-cut route for the scint fit: "fft" (padded 1-D FFTs, VPU),
    # "matmul" (Gram-matrix diagonal sums, MXU), or "auto" (matmul on
    # TPU — measured ~2x faster there — fft elsewhere).  Only applies to
    # the direct-cuts fast path; when return_acf/fit_scint_2d force the
    # full 2-D ACF anyway, the fit reads its cuts from that ACF and this
    # knob is irrelevant.
    scint_cuts: str = "auto"
    ref_freq: float = 1400.0
    return_acf: bool = False
    return_sspec: bool = False
    # I/O precision policy: "f32" (default — the staging/transfer dtype
    # is unchanged, compute as today) or "bf16_io" — the dynspec batch
    # is CONVERTED to bfloat16 host-side, transferred and held in HBM
    # at 2 bytes/element (halving bytes_h2d and the step's first-stage
    # reads), and upcast to float32 at the top of the compiled step so
    # every FFT/matmul/accumulation still runs in f32.  Parity budget
    # vs f32 on synthetic epochs is tier-1-tested
    # (tests/test_precision.py) and documented in docs/performance.md.
    precision: str = "f32"
    # Padded FFT lengths for the secondary spectrum: "pow2" (the
    # reference's next-pow2-doubled rule — the parity path) or "fast"
    # (smallest even 5-smooth composite >= 2n per axis, never longer
    # than pow2; changes the spectral sampling, so fdop/tdel grids and
    # arc fits shift within their errors).  The 2-D ACF path pads
    # "fast" to composite lengths too, centre-cropped back — those
    # values are unchanged (ops/acf.py).
    fft_lens: str = "pow2"
    # Fuse the arc fitter's delay-window crop into the compiled step:
    # the secondary spectrum's postdark/dB tail (and the step output)
    # only materialise the rows the norm_sspec fitter consumes, so the
    # full padded spectrum never round-trips HBM.  eta is bit-identical
    # (the profile rows and eta grid are unchanged); etaerr's noise
    # window shrinks to the cropped grid, so errors differ slightly —
    # hence opt-in.  Requires fit_arc + norm_sspec and no return_sspec.
    sspec_crop: bool = False
    # Fused secondary-spectrum kernels (ops/sspec_pallas): prologue
    # (mean-sub + window + prewhiten + pad in ONE FFT-input write) and
    # epilogue (|.|^2 + fftshift + postdark + dB + delay crop,
    # tile-by-tile) as Pallas kernels on a real TPU, an equivalently-
    # restructured XLA lowering elsewhere; with sspec_crop the delay
    # transform shrinks to an R-row DFT matmul and the full padded
    # spectrum is never materialised (measured cost_analysis() bytes
    # -36 % at the 256x512 crop signature — docs/performance.md "Fused
    # kernels").  Opt-in: NOT bit-identical to the chain (fits agree
    # within the 2 % budget); default off keeps every existing output
    # byte-identical.  CLI: --fused-sspec.
    fused_sspec: bool = False
    # Compile-unit splitting (ISSUE 14): run the step as TWO separately
    # jit-compiled, separately cached program units — a shape-volatile
    # FRONT-END (generate/upcast → lambda resample → sspec transform →
    # ACF cuts + normalised arc profile) keyed on the full (axes, nf,
    # nt, B, dtype) signature, and a shape-stable BACK-END (the scint
    # LM fit over canonicalised tail-padded cut vectors, the arc
    # measurement tail over fixed-length norm_sspec profiles) keyed
    # only on its canonicalised intermediate signature (a closed mini
    # ladder of padded lengths — buckets.vector_rung).  A novel
    # (nf, nt) then recompiles only the front-end slice; the fitter
    # programs serve warm (back-end jit_cache_miss == 0,
    # tier-1-asserted).  Device-resident handoff (no host round-trip);
    # results are BIT-identical to the fused single program (the CSV
    # byte-equality gate), so this is a placement knob: serve job
    # identity strips it like `bucket`.  Default off — the fused
    # single program stays the bit-matching default.  CLI:
    # --split-programs.  Requires the standard survey config (norm_
    # sspec arc fitting, no return_acf/return_sspec/fit_scint_2d/
    # arc_stack, no chan-sharded mesh — PipelineConfig.validate).
    split_programs: bool = False

    def validate(self, mesh=None, chan_sharded: bool | None = None) -> None:
        """Reject config combinations the traced step cannot honour —
        the ONE rule site (ISSUE 14 satellite) shared by
        :func:`make_pipeline` (driver/CLI) and
        ``serve.queue.validate_job_cfg`` (client submit), so
        split/crop/arc rules cannot drift between CLI, driver and
        serve.  ``mesh``/``chan_sharded`` gate the sharding-dependent
        rules; a meshless validation (the serve client's) simply skips
        them."""
        if self.scint_cuts not in ("auto", "fft", "matmul"):
            raise ValueError(
                f"PipelineConfig.scint_cuts: unknown method "
                f"{self.scint_cuts!r} (expected 'auto', 'fft' or "
                f"'matmul')")
        if (self.arc_scrunch_rows != "pallas"
                and (isinstance(self.arc_scrunch_rows, str)
                     or self.arc_scrunch_rows < -1)):
            raise ValueError(
                f"PipelineConfig.arc_scrunch_rows must be -1 (auto), 0 "
                f"(full gather), a positive block size or 'pallas', got "
                f"{self.arc_scrunch_rows}")
        if self.arc_tail not in ("exact", "fast"):
            raise ValueError(
                f"PipelineConfig.arc_tail must be 'exact' or 'fast', "
                f"got {self.arc_tail!r}")
        if self.arc_method not in ("norm_sspec", "gridmax", "thetatheta"):
            raise ValueError(
                f"PipelineConfig.arc_method: unknown method "
                f"{self.arc_method!r} (expected 'norm_sspec', 'gridmax' "
                f"or 'thetatheta')")
        if self.precision not in ("f32", "bf16_io"):
            raise ValueError(
                f"PipelineConfig.precision: unknown policy "
                f"{self.precision!r} (expected 'f32' or 'bf16_io')")
        if self.fft_lens not in ("pow2", "fast"):
            raise ValueError(
                f"PipelineConfig.fft_lens: unknown mode "
                f"{self.fft_lens!r} (expected 'pow2' or 'fast')")
        if self.sspec_crop and (not self.fit_arc or self.return_sspec
                                or self.arc_method != "norm_sspec"):
            raise ValueError(
                "PipelineConfig.sspec_crop fuses the norm_sspec fitter's "
                "delay-window crop into the step: it requires "
                "fit_arc=True with arc_method='norm_sspec' and "
                "return_sspec=False (a returned spectrum must be the "
                "full grid)")
        if self.fused_sspec and _resolve_chan_sharded(mesh, chan_sharded):
            raise ValueError(
                "PipelineConfig.fused_sspec does not support a "
                "chan-sharded mesh yet: the fused kernels tile a single "
                "device's spectrum (the channel-sharded FFT path keeps "
                "the unfused chain)")
        if self.arc_stack and (self.arc_method != "norm_sspec"
                               or not self.fit_arc
                               or self.arc_brackets is not None):
            raise ValueError(
                "PipelineConfig.arc_stack requires fit_arc=True with "
                "arc_method='norm_sspec' and no arc_brackets (the "
                "campaign stack averages ONE normalised profile per "
                "epoch)")
        if self.split_programs:
            if self.fit_arc and self.arc_method != "norm_sspec":
                raise ValueError(
                    "PipelineConfig.split_programs splits the step at "
                    "the norm_sspec profile boundary: arc_method="
                    f"{self.arc_method!r} has no shape-stable fitter "
                    "unit yet (use 'norm_sspec', or drop the split)")
            if (self.return_acf or self.return_sspec
                    or self.fit_scint_2d or self.arc_stack):
                raise ValueError(
                    "PipelineConfig.split_programs supports the "
                    "standard survey step only: return_acf/return_sspec"
                    "/fit_scint_2d/arc_stack keep shape-volatile grids "
                    "past the split boundary (drop them, or drop the "
                    "split)")
            if _resolve_chan_sharded(mesh, chan_sharded):
                raise ValueError(
                    "PipelineConfig.split_programs does not support a "
                    "chan-sharded mesh: the handoff intermediates are "
                    "batch-sharded only")
        if self.arc_method == "thetatheta" and self.fit_arc:
            windows = (self.arc_brackets
                       if self.arc_brackets is not None
                       else (self.arc_constraint,))
            if len(windows) == 0:
                raise ValueError("arc_brackets must contain at least "
                                 "one (lo, hi) window")
            for lo, hi in windows:
                if not (np.isfinite(lo) and np.isfinite(hi)
                        and 0 < lo < hi):
                    raise ValueError(
                        "arc_method='thetatheta' sweeps its curvature "
                        f"bracket(s), which must be finite and "
                        f"positive, got {tuple(windows)} (units follow "
                        "the spectrum: beta-eta for lamsteps, us/mHz^2 "
                        "otherwise, as fit_arc_thetatheta)")
            if self.arc_asymm:
                raise ValueError(
                    "arc_method='thetatheta' does not support arc_asymm "
                    "(the concentration sweep has no per-arm split)")
            # knobs of the power-profile fitters that the concentration
            # sweep has no analogue for: reject loudly, never ignore
            _def = PipelineConfig()
            ignored = [name for name, val, dflt in (
                ("arc_delmax", self.arc_delmax, _def.arc_delmax),
                ("arc_nsmooth", self.arc_nsmooth, _def.arc_nsmooth),
                ("arc_scrunch_rows", self.arc_scrunch_rows,
                 _def.arc_scrunch_rows),
                ("arc_tail", self.arc_tail, _def.arc_tail),
            ) if val != dflt]
            if ignored:
                raise ValueError(
                    f"arc_method='thetatheta' has no equivalent of "
                    f"{', '.join(ignored)} (norm_sspec/gridmax knobs); "
                    "leave them at their defaults")


@dataclasses.dataclass(frozen=True)
class PipelineResult:
    """Per-epoch measurements from one batched step ([B]-leading leaves)."""

    scint: Any = None       # ScintParams with [B] leaves
    arc: Any = None         # ArcFit with [B] leaves
    acf: Any = None         # [B, 2nf, 2nt] when requested
    sspec: Any = None       # [B, nr, nc] when requested
    fdop: Any = None
    tdel: Any = None
    beta: Any = None
    scint2d: Any = None     # ScintParams from the 2-D fit (fit_scint_2d)
    tilt: Any = None        # [B] phase-gradient tilt (s/MHz)
    tilterr: Any = None
    arc_stacked: Any = None  # scalar ArcFit (campaign stack, arc_stack)


def _register():
    try:
        import jax

        jax.tree_util.register_pytree_node(
            PipelineResult,
            lambda r: ((r.scint, r.arc, r.acf, r.sspec, r.fdop, r.tdel,
                        r.beta, r.scint2d, r.tilt, r.tilterr,
                        r.arc_stacked), None),
            lambda _, l: PipelineResult(*l))
    except ImportError:  # pragma: no cover
        pass


_register()


def lambda_resample_matrix(freqs: np.ndarray) -> tuple[np.ndarray, np.ndarray, float]:
    """Precompute the freq→uniform-lambda natural-cubic-spline resampling
    as a dense matrix W so that ``lamdyn = W @ dyn`` (rows already flipped
    to descending wavelength, matching ops.scale.scale_lambda / reference
    dynspec.py:1427-1428).  Spline interpolation is linear in the data, so
    W columns are the splines of the unit vectors."""
    from ..ops.scale import natural_cubic_interp_numpy
    from ..data import _C_M_S

    freqs = np.asarray(freqs, dtype=np.float64)  # host-f64: host spline precompute
    lam_eq, dlam = lambda_grid(freqs)
    feq = _C_M_S / lam_eq / 1e6
    eye = np.eye(len(freqs))
    # host-side numpy transcription of the jax natural-spline solver:
    # building the pipeline must not execute anything on the device
    # (the accelerator may be deliberately untouched at build time)
    W = natural_cubic_interp_numpy(eye, freqs, feq)  # [nlam, nf]
    return W[::-1].copy(), lam_eq[::-1].copy(), float(dlam)


def _validate_synth_config(config: "PipelineConfig", mesh,
                           chan_sharded: bool | None) -> None:
    """Config combinations the synthetic route rejects — ONE rule site
    shared by make_pipeline and run_pipeline (and, through the serve
    submit validation, the job queue), so a bad campaign fails at the
    caller with the same message everywhere."""
    if config.precision != "f32":
        raise ValueError(
            "the synthetic route generates the dynspec batch on-device:"
            " precision='bf16_io' has no host transfer to halve (and "
            "would fork the step identity for nothing); use the "
            "default 'f32'")
    if config.arc_stack:
        raise ValueError(
            "arc_stack is not supported on the synthetic route: its "
            "pad lanes are real re-simulations (keys cannot be "
            "NaN-filled), which would bias the campaign stack")
    if _resolve_chan_sharded(mesh, chan_sharded):
        raise ValueError(
            "the synthetic route does not support a chan-sharded mesh "
            "yet: the generator materialises each epoch on one device "
            "(shard the batch axis over `data`)")


def _resolve_chan_sharded(mesh, chan_sharded: bool | None) -> bool:
    """chan_sharded=None derivation rule — the single source of truth
    for make_pipeline's in_shardings and run_pipeline's host-side
    global-array assembly (they must agree, or multihost batches pay a
    resharding collective every step): any mesh with a >1 ``chan`` axis
    shards the secondary-spectrum FFT's channel axis."""
    if chan_sharded is None:
        return (mesh is not None
                and int(mesh.shape.get(mesh_mod.CHAN_AXIS, 1)) > 1)
    return bool(chan_sharded)


def make_pipeline(freqs, times, config: PipelineConfig = PipelineConfig(),
                  mesh=None, chan_sharded: bool | None = None,
                  donate: bool = False, synth=None):
    """Build the jit'd batched step for a fixed (freqs, times) template.

    ``synth`` (a :class:`scintools_tpu.sim.campaign.SynthSpec`) fuses
    the on-device generator into the step: the compiled program's input
    becomes the campaign's uint32 key batch ``[B, 2+F]`` and the
    dynspec batch is generated in HBM at the top of the SAME program —
    the zero-H2D synthetic route.  The spec is canonicalised to its
    program identity (``campaign.generator_id``) before it enters the
    jit memo, so campaigns differing only in epoch count / seed / sweep
    values share one compiled step.

    ``chan_sharded=None`` (default) derives channel sharding from the
    mesh itself: any mesh with a >1 ``chan`` axis shards the
    secondary-spectrum FFT's channel axis (why else build one).  Pass an
    explicit bool to override.  ``donate=True`` donates the input batch
    buffer to the step (``donate_argnums``) so XLA reuses its HBM for
    intermediates — only safe when each call gets a fresh buffer, as the
    async chunk executor guarantees (schedule.py); donation is a no-op
    with a warning on CPU, so the driver only requests it on TPU.

    Returns ``step(dyn_batch [B, nf, nt]) -> PipelineResult``.  Epochs with
    other shapes go through parallel.batch.pad_batch / bucket_by_shape
    first.  dt/df are taken from the template axes (uniform grids, as the
    reference assumes — dynspec.py:1291-1299).

    Memoised on (axes, config, mesh, donate): repeated calls with the same
    template return the same compiled step (no retrace/recompile per
    survey batch).
    """
    # ONE rule site (PipelineConfig.validate — shared with the serve
    # client's submit validation), so a bad config fails identically
    # from the CLI, the driver and a queued job
    config.validate(mesh=mesh, chan_sharded=chan_sharded)
    if synth is not None:
        from ..sim import campaign

        campaign.validate_spec(synth)
        _validate_synth_config(config, mesh, chan_sharded)
        synth = campaign.generator_id(synth)
    freqs = np.ascontiguousarray(np.asarray(freqs, dtype=np.float64))  # host-f64: host axes (cache key)
    times = np.ascontiguousarray(np.asarray(times, dtype=np.float64))  # host-f64: host axes (cache key)
    return _make_pipeline_cached(
        (freqs.tobytes(), freqs.shape), (times.tobytes(), times.shape),
        config, mesh, _resolve_chan_sharded(mesh, chan_sharded),
        bool(donate), synth)


# "auto" falls back to the FFT route above this many bytes of Gram-matrix
# working set: the matmul route materialises [B, nf, nf] + [B, nt, nt]
# (the FFT route stays O(nf*nt) per epoch), so long axes must not OOM a
# pipeline that worked before the auto default existed.
_AUTO_MATMUL_GRAM_BYTE_CAP = 1 << 30


def _gram_bytes(batch_shape, mesh, itemsize: int) -> int:
    """Per-device bytes the matmul cuts route would materialise: the
    [b, nf, nf] + [b, nt, nt] Gram matrices, with the batch axis divided
    over the mesh's data axis when sharded."""
    b = int(np.prod(batch_shape[:-2], dtype=np.int64))
    if mesh is not None:
        b = -(-b // int(mesh.shape.get(mesh_mod.DATA_AXIS, 1)))
    nf, nt = int(batch_shape[-2]), int(batch_shape[-1])
    return itemsize * b * (nf * nf + nt * nt)


def _target_is_tpu(mesh) -> bool:
    """Whether the execution target (the mesh's devices, or the default
    device set) is a TPU.  Called at TRACE time only — never at
    pipeline-build time, so building stays device-free."""
    import jax

    try:
        devs = (list(mesh.devices.flat) if mesh is not None
                else jax.devices())
        d = devs[0]
        kind = str(getattr(d, "device_kind", "")).lower()
        return "tpu" in kind or d.platform in ("tpu", "axon")
    except Exception:  # fault-ok: capability probe (no backend => not TPU)
        return False


def _resolve_cuts(method: str, mesh, batch_shape=None,
                  itemsize: int = 4) -> str:
    """Resolve scint_cuts="auto" per target hardware: the MXU Gram route
    is ~2x the FFT route on TPU (measured, docs/performance.md) and has
    no advantage on CPU.  Called at TRACE time (inside the first step
    call), never at pipeline-build time, so building stays device-free."""
    if method not in ("auto", "fft", "matmul"):
        raise ValueError(f"scint_cuts: unknown method {method!r} "
                         "(expected 'auto', 'fft' or 'matmul')")
    if method != "auto":
        return method
    if (batch_shape is not None
            and _gram_bytes(batch_shape, mesh, itemsize)
            > _AUTO_MATMUL_GRAM_BYTE_CAP):
        return "fft"
    return "matmul" if _target_is_tpu(mesh) else "fft"


# auto routes for arc_scrunch_rows=-1: on chip the fused Pallas kernel
# (round-4 A/B at the bench shape: 3.5x the 64-row scan, numerics
# agreeing to 1e-7; non-conforming Doppler widths demote to scan-64
# inside the fitter).  On host CPU the scan with the LARGEST block the
# working-set cap allows: since the GEMM-reduction body (round 5,
# ops/resample_pallas.py) bigger blocks win — the round-5 micro-bench
# at B=64, 256x512 measured one-block 507 ms vs 16-row 615-696 ms —
# but each block materialises a [B_local, 4*block, numsteps] f32
# stack, so the block is sized to keep that under the cap (the old
# round-3 fixed 16 is the floor; that measurement predates the GEMM
# body and its 16-beats-64 ordering no longer holds).
_AUTO_ARC_SCRUNCH_TPU = "pallas"
_AUTO_ARC_SCRUNCH_CPU_MIN = 16
_AUTO_ARC_SCRUNCH_CPU_MAX = 256
_AUTO_ARC_SCRUNCH_BYTE_CAP = 256 * 1024 * 1024


def _resolve_arc_scrunch(config: "PipelineConfig", mesh,
                         batch_shape=None, itemsize: int = 4):
    """arc_scrunch_rows=-1 auto rule — the single source of truth shared
    by the step builder and the recorded route metadata.  Resolved at
    TRACE time (like _resolve_cuts), never at build time.  Returns a
    block-size int or the route string "pallas".

    ``batch_shape`` (the traced [B, nf, nt], when known) sizes the CPU
    scan block: the largest one whose per-block masked stack
    [B_local, 4*block, arc_numsteps] (at ``itemsize`` bytes/element)
    stays under the byte cap, clamped to [16, 256] with a pow2 floor.
    Unknown shape falls back to the floor."""
    rc = config.arc_scrunch_rows
    if rc != -1:
        return rc if rc == "pallas" else int(rc)
    if _target_is_tpu(mesh):
        return _AUTO_ARC_SCRUNCH_TPU
    blk = _AUTO_ARC_SCRUNCH_CPU_MIN
    if batch_shape is not None:
        b = int(np.prod(batch_shape[:-2], dtype=np.int64))
        if mesh is not None:
            b = -(-b // int(mesh.shape.get(mesh_mod.DATA_AXIS, 1)))
        per_row = (4 * max(int(config.arc_numsteps), 1)
                   * int(itemsize) * max(b, 1))
        fit = int(min(_AUTO_ARC_SCRUNCH_CPU_MAX,
                      max(_AUTO_ARC_SCRUNCH_CPU_MIN,
                          _AUTO_ARC_SCRUNCH_BYTE_CAP // per_row)))
        blk = 1 << (fit.bit_length() - 1)   # pow2 floor: stable shapes
    return blk


def resolve_routes(config: "PipelineConfig", mesh=None,
                   batch_shape=None, itemsize: int = 4) -> dict:
    """The concrete routes this host's execution target resolves the
    config's ``auto`` knobs to, plus the target platform.

    Record this beside a resumable survey: the matmul and FFT ACF-cut
    routes differ at f32 contraction rounding, so the same store resumed
    on a different host class can yield numerically drifted tau/dnu.
    The CLI writes this dict as store metadata and logs a
    ``routes_changed`` event when a resume sees a different resolution.
    """
    return {"scint_cuts": _resolve_cuts(config.scint_cuts, mesh,
                                        batch_shape, itemsize),
            "arc_scrunch_rows": _resolve_arc_scrunch(config, mesh,
                                                     batch_shape,
                                                     itemsize),
            "target_is_tpu": bool(_target_is_tpu(mesh))}


def _bucket_epochs(epochs) -> dict:
    """Group epoch indices by shape AND axis identity.  The single
    source of truth for run_pipeline's bucketing (two epochs with equal
    (nf, nt) but different bands/sampling must not share a pipeline:
    its df/fc/lambda grid are baked in host-side from the template
    axes); survey_routes uses the same grouping so recorded routes
    describe exactly the batches that get traced."""
    from collections import defaultdict

    buckets: dict[tuple, list[int]] = defaultdict(list)
    for i, d in enumerate(epochs):
        f = np.asarray(d.freqs, dtype=np.float64)  # host-f64: host bucketing key
        t = np.asarray(d.times, dtype=np.float64)  # host-f64: host bucketing key
        buckets[(f.shape, t.shape, f.tobytes(), t.tobytes())].append(i)
    return buckets


def _adjust_chunk(multiple: int, chunk: int) -> int:
    """Largest mesh-divisible chunk size <= chunk (but >= one multiple);
    shared by run_pipeline's chunk loop and survey_routes."""
    return max(multiple, (chunk // multiple) * multiple)


def _step_batch_sizes(B: int, multiple: int, chunk: int | None,
                      pad_chunks: bool = False) -> set:
    """The set of per-step batch sizes run_pipeline's chunk loop issues
    for a padded bucket of B epochs (an uneven final chunk traces as its
    own program and may resolve auto routes differently).  With
    ``pad_chunks`` the final chunk is padded up to the chunk size, so a
    chunked survey compiles exactly ONE program."""
    if chunk is None or chunk >= B:
        return {B}
    c = _adjust_chunk(multiple, chunk)
    if pad_chunks:
        return {c}
    return {c} | ({B % c} if B % c else set())


def survey_routes(epochs, config: "PipelineConfig", mesh=None,
                  chunk: int | None = None,
                  pad_chunks: bool = False) -> dict:
    """Per-bucket resolved routes for a ``run_pipeline`` call with the
    same arguments — the metadata the CLI records beside a resumable
    store.  Shares run_pipeline's bucketing (_bucket_epochs),
    divisibility padding and chunk math (_step_batch_sizes) so the
    recorded ``scint_cuts`` matches what each traced step actually
    resolves (the auto route depends on the per-step padded batch shape
    through the Gram byte cap).

    Keys are descriptive (``bucket<k>:<n>of<nf>x<nt>:step<b>``) and
    depend on batch composition; drift comparisons must compare the
    route *values* (see the CLI's resume check), not the keys.
    """
    multiple = 1
    if mesh is not None:
        multiple = mesh.shape[mesh_mod.DATA_AXIS]
    out = {}
    for k, (key, idx) in enumerate(_bucket_epochs(epochs).items()):
        (nf,), (nt,) = key[0], key[1]
        n = len(idx)
        B = -(-n // multiple) * multiple
        for b in sorted(_step_batch_sizes(B, multiple, chunk,
                                          pad_chunks=pad_chunks)):
            out[f"bucket{k}:{n}of{nf}x{nt}:step{b}"] = resolve_routes(
                config, mesh, batch_shape=(b, nf, nt))
    return out


# ---------------------------------------------------------------------------
# compile-unit splitting (ISSUE 14): shape-volatile front-end vs
# shape-stable fitter back-end as separately compiled, separately
# cached program units.
# ---------------------------------------------------------------------------

# config fields ONLY the back-end (fitter) unit reads: they are pinned
# to defaults in the front-end's cache key (changing a fitter knob must
# not invalidate the transform programs) and enter the back-end's key
# via split_backend_desc.  arc_delmax/arc_startbin/arc_cutmid/
# arc_constraint/arc_brackets shape the profile EXTRACTION or arrive as
# runtime grid inputs, so they are deliberately NOT here (brackets'
# window COUNT is back-program structure and rides in the desc below).
_SPLIT_BACK_ONLY_FIELDS = ("alpha", "lm_steps", "arc_nsmooth", "arc_tail",
                           "arc_asymm")


def _front_config(config: "PipelineConfig") -> "PipelineConfig":
    """The split front-end unit's program identity: the full config
    with the fitter-only knobs pinned to defaults (the front-end trace
    never reads them, so two configs differing only there must share
    one front artifact)."""
    d = PipelineConfig()
    return dataclasses.replace(
        config, **{f: getattr(d, f) for f in _SPLIT_BACK_ONLY_FIELDS})


def split_backend_desc(config: "PipelineConfig") -> tuple:
    """The split back-end unit's program identity: everything that
    changes its traced program and NOTHING axes-derived (the point of
    the split).  Doubles as the `_make_split_backend` cache key and a
    component of ``compile_cache.split_backend_key``.  Grid-derived
    values (eta array, validity window, constraint masks, lag axes)
    arrive as runtime inputs and are absent by design."""
    return (bool(config.fit_scint), config.alpha, int(config.lm_steps),
            bool(config.fit_arc), int(config.arc_numsteps),
            int(config.arc_nsmooth), bool(config.arc_asymm),
            None if config.arc_brackets is None
            else len(config.arc_brackets),
            str(config.arc_tail), bool(config.lamsteps))


@functools.lru_cache(maxsize=None)
def _make_split_backend(back_desc: tuple):
    """ONE jit'd fitter program per back identity, shared across every
    axes/(nf, nt) signature — the module-level cache (and, under
    obs.instrument_jit, the shared wrapper whose compiled-signature
    memo) is what makes a novel dynspec shape hit
    ``jit_cache_miss[pipeline.back] == 0``."""
    import jax

    (fit_scint, alpha, lm_steps, fit_arc, numsteps, nsmooth, asymm,
     n_windows, arc_tail, lamsteps) = back_desc

    measure = None
    if fit_arc:
        from ..fit.arc_fit import make_profile_measurer

        measure = make_profile_measurer(
            numsteps, nsmooth=nsmooth, noise_error=True, asymm=asymm,
            n_windows=n_windows, arc_tail=arc_tail)

    def back(parts):
        scint = arc = None
        if fit_scint:
            from ..fit.scint_fit import fit_scint_params_cat

            # optional RUNTIME iteration bound (streaming warm-started
            # ticks): presence of the key selects the dynamic trace of
            # this same shared jit — the compile cache key (back_desc)
            # is untouched, and steady-state streaming always passes
            # the key, so the warm signature is stable
            scint = fit_scint_params_cat(
                parts["scint_y"], parts["scint_p0"],
                parts["scint_nobs"], parts["scint_x"],
                parts["scint_is_t"], parts["scint_spike"],
                parts["scint_xmax"], parts["scint_valid"],
                alpha=alpha, steps=lm_steps,
                steps_rt=parts.get("lm_steps_rt"))
        if fit_arc:
            from ..fit.arc_fit import pack_measurement

            res = jax.vmap(measure, in_axes=(0, 0, None, None, None))(
                parts["prof"], parts["noise"], parts["arc_eta"],
                parts["arc_keep"], parts["arc_cmasks"])
            arc = pack_measurement(res, lamsteps, parts["arc_eta"],
                                   asymm=asymm)
        return {"scint": scint, "arc": arc}

    return jax.jit(back)


def _split_profile_len(numsteps: int) -> int:
    """Length of the folded eta-grid arm (= the eta array / masks the
    back unit consumes) — the same ``ipos`` rule the fitter uses."""
    n = int(numsteps)
    etafrac = np.linspace(-1.0, 1.0, n)
    return int(np.sum(etafrac > 1 / (2 * n)))


class _SplitStep:
    """The split pipeline step: front jit + (shared) back jit with a
    device-resident handoff, behind the same ``step(dyn) ->
    PipelineResult`` contract as the fused program.  Carries the
    per-unit cache keys so run_pipeline/warmup can load/export each
    unit's ``.jaxexec`` artifact independently."""

    unit_front = "pipeline.front"
    unit_back = "pipeline.back"

    def __init__(self, front, back, aux: dict, result_fn, back_desc,
                 key_parts, dims: dict):
        self.front = front            # jit'd, per-axes
        self.back = back              # jit'd, SHARED per back_desc
        self._aux_np = aux            # canonical-dtype numpy grid inputs
        self._aux_dev = None          # lazy device residency (one H2D)
        self._result = result_fn
        self.back_desc = back_desc
        self._key_parts = key_parts   # (freqs, times, config, mesh,
        #                                chan_sharded, donate, genid)
        self.dims = dims              # rung/profile lengths for specs

    # -- cache keys / AOT specs -----------------------------------------
    def front_key(self, batch_shape, dtype) -> str:
        from .. import compile_cache

        f, t, cfg, mesh, chan, donate, genid = self._key_parts
        return compile_cache.step_key(
            f, t, _front_config(cfg), mesh, chan, batch_shape, dtype,
            donate=donate, synth=genid, unit="front")

    def back_sig(self, b: int) -> tuple:
        """Canonical (name, shape, dtype) tuple of the back unit's
        input pytree at batch size ``b`` — the shape component of its
        artifact key."""
        spec = self.back_spec(b)
        return tuple(sorted((k, tuple(int(s) for s in v.shape),
                             str(v.dtype)) for k, v in spec.items()))

    def back_key(self, b: int) -> str:
        from .. import compile_cache

        return compile_cache.split_backend_key(self.back_desc,
                                               self.back_sig(b))

    def back_aot_eligible(self) -> bool:
        """Whether the back unit may be served from / exported as a
        serialized executable.  Under a >1-device mesh the front jit's
        outputs are batch-SHARDED arrays, but ``back_spec`` describes
        plain single-device inputs (the axes-free key deliberately
        carries no mesh): a deserialized executable would reject the
        sharded handoff and instrument_jit's fallback would silently
        re-pay the cold compile while still counting warm.  The live
        back jit handles sharded inputs fine, so meshed runs simply
        skip the artifact layer for this unit."""
        mesh = self._key_parts[3]
        return mesh is None or int(np.prod(list(
            dict(mesh.shape).values()))) == 1

    def back_spec(self, b: int) -> dict:
        """ShapeDtypeStruct pytree of the back unit's input dict."""
        import jax

        # the canonical float dtype this runtime computes in (f32 under
        # the production x64-off runtime, f64 under x64 test configs)
        fdt = jax.dtypes.canonicalize_dtype(np.float64)  # host-f64: canonicalisation probe
        S = jax.ShapeDtypeStruct
        spec = {}
        d = self.dims
        if "scint_rung" in d:
            L = d["scint_rung"]
            spec.update(scint_y=S((b, L), fdt), scint_x=S((b, L), fdt),
                        scint_xmax=S((b, L), fdt), scint_p0=S((b, 4), fdt),
                        scint_is_t=S((L,), np.dtype(bool)),
                        scint_spike=S((L,), np.dtype(np.float32)),
                        scint_valid=S((L,), np.dtype(bool)),
                        scint_nobs=S((), np.dtype(np.float32)))
        if "arc_profile" in d:
            n, m, k = d["arc_profile"], d["arc_grid"], d["arc_windows"]
            spec.update(prof=S((b, n), fdt), noise=S((b,), fdt),
                        arc_eta=S((m,), fdt),
                        arc_keep=S((m,), np.dtype(bool)),
                        arc_cmasks=S((k, m), np.dtype(bool)))
        return spec

    # -- execution -------------------------------------------------------
    def _aux_device(self) -> dict:
        if self._aux_dev is None:
            import jax

            self._aux_dev = {k: jax.device_put(v)
                             for k, v in self._aux_np.items()}
        return self._aux_dev

    def bind(self, front_fn=None, back_fn=None):
        """A plain ``step(dyn)`` callable over the given unit
        implementations (live jit by default; AOT-loaded executables
        from run_pipeline's per-unit artifact lookup)."""
        ffn = self.front if front_fn is None else front_fn
        bfn = self.back if back_fn is None else back_fn

        def call(dyn_batch):
            parts = ffn(dyn_batch)
            full = dict(parts)
            full.update(self._aux_device())
            return self._result(bfn(full))

        return call

    def bind_parts(self, parts, back_fn=None):
        """Run ONLY the back unit + result packing over an externally
        computed ``parts`` dict (the streaming plane's incremental
        front hands its sliding-window update results straight to the
        shared fitter program, bypassing ``self.front``)."""
        bfn = self.back if back_fn is None else back_fn
        full = dict(parts)
        full.update(self._aux_device())
        return self._result(bfn(full))

    def instrumented_back(self):
        """The shared back jit under the SAME obs wrapper
        ``instrumented()`` uses (instrument_jit memoises on the
        function object): per-signature ``jit_cache_miss`` accounting
        is common, so an incremental tick's back call is warm iff the
        full path already compiled that signature."""
        return obs.instrument_jit(self.back, self.unit_back)

    def instrumented(self, front_aot=None, back_aot=None):
        """The composed step with per-unit obs accounting: compile/
        execute spans and ``compile_ms``/``jit_cache_miss`` series land
        under ``pipeline.front`` / ``pipeline.back`` instead of one
        opaque ``pipeline.step`` — `trace report`'s compile profile
        then shows exactly which slice a novel shape recompiled.
        ``front_aot``/``back_aot``: artifact-loaded unit executables
        (instrumented as warm)."""
        f = (obs.instrument_jit(self.front, self.unit_front)
             if front_aot is None
             else obs.instrument_jit(front_aot, self.unit_front,
                                     aot=True))
        b = (obs.instrument_jit(self.back, self.unit_back)
             if back_aot is None
             else obs.instrument_jit(back_aot, self.unit_back,
                                     aot=True))
        return self.bind(front_fn=f, back_fn=b)

    def __call__(self, dyn_batch):
        return self.bind()(dyn_batch)


@functools.lru_cache(maxsize=None)
def _make_pipeline_cached(freqs_key, times_key, config, mesh, chan_sharded,
                          donate=False, synth=None):
    import jax
    import jax.numpy as jnp

    freqs = np.frombuffer(freqs_key[0]).reshape(freqs_key[1])
    times = np.frombuffer(times_key[0]).reshape(times_key[1])
    nchan, nsub = len(freqs), len(times)
    gen_fn = None
    if synth is not None:
        from ..sim.campaign import synth_generator, synth_shape

        if synth_shape(synth) != (nchan, nsub):
            raise ValueError(
                f"synthetic generator grid {synth_shape(synth)} does "
                f"not match the template axes ({nchan}, {nsub})")
        gen_fn = synth_generator(synth)
    df = float(freqs[1] - freqs[0])
    dt = float(times[1] - times[0])
    fc = float(np.mean(freqs))

    if config.lamsteps:
        W, lam, dlam = lambda_resample_matrix(freqs)
        nf_s = W.shape[0]
        # stays numpy here: jnp.asarray inside the traced step embeds it
        # as a compile-time constant instead of an eager device_put
        # (building a pipeline must not touch the device)
        W_np = W
    else:
        W_np, dlam = None, None
        nf_s = nchan

    fdop, tdel, beta = sspec_axes(nf_s, nsub, dt, df, dlam=dlam,
                                  lens=config.fft_lens)
    fdop = np.asarray(fdop, dtype=np.float64)    # host-f64: grid builder
    tdel = np.asarray(tdel, dtype=np.float64)    # host-f64: grid builder
    acf_lens = "fast" if config.fft_lens == "fast" else "exact"

    # Fused arc-window crop (sspec_crop): the norm_sspec fitter consumes
    # delay rows [0, max(ind, ind_norm)] only, so the sspec op can stop
    # materialising (and postdark/dB-converting) anything beyond them.
    # The fitter is rebuilt on the cropped axes with delmax PINNED to
    # the pre-adjustment value, which the shared row-window rule
    # guarantees resolves to the same indices — eta is bit-identical,
    # only the noise-estimate window (rows R/2:) shrinks with the grid.
    crop_rows = None
    arc_delmax = config.arc_delmax
    if config.sspec_crop:
        from ..fit.arc_fit import norm_sspec_row_window

        ind_c, ind_n, dmax_raw = norm_sspec_row_window(
            tdel, fc, ref_freq=config.ref_freq, delmax=config.arc_delmax)
        rows = min(len(tdel), max(ind_c, ind_n) + 1)
        if rows < len(tdel):
            crop_rows = rows
            arc_delmax = dmax_raw
    yaxis_fit = (beta if config.lamsteps else tdel)
    tdel_fit = tdel
    if crop_rows is not None:
        yaxis_fit = np.asarray(yaxis_fit)[:crop_rows]
        tdel_fit = tdel_fit[:crop_rows]

    def build_arc_fitter(batch_shape=None, itemsize: int = 4):
        # called at TRACE time (inside the first step call), so the
        # scrunch auto-default may probe the execution target AND see
        # the traced batch shape; building the pipeline itself stays
        # device-free
        if config.arc_method == "thetatheta":
            from ..fit.thetatheta import make_tt_fitter

            # arc_numsteps' 2000-point default sizes the norm_sspec eta
            # grid; a 2000-iteration remap+power-iteration sweep is ~15x
            # the documented-sufficient theta-theta sweep, so an
            # untouched default becomes 128 here (explicit values win)
            n_eta = config.arc_numsteps
            if n_eta == PipelineConfig().arc_numsteps:
                n_eta = 128

            def make_one(lo, hi):
                return make_tt_fitter(
                    fdop=fdop, yaxis=beta if config.lamsteps else tdel,
                    etamin=float(lo), etamax=float(hi),
                    n_eta=n_eta, ntheta=config.arc_ntheta,
                    startbin=config.arc_startbin,
                    cutmid=config.arc_cutmid, lamsteps=config.lamsteps)

            if config.arc_brackets is None:
                return make_one(*config.arc_constraint)
            # multi-arc: one bounded sweep per bracket, stacked to the
            # same [B, K] result shape as norm_sspec's multi-window fit;
            # each bracket keeps its own eta grid ([K, n_eta] profiles)
            fitters = [make_one(lo, hi) for lo, hi in config.arc_brackets]

            def multi(sec_b):
                from ..data import ArcFit

                fits = [f(sec_b) for f in fitters]
                return ArcFit(
                    eta=jnp.stack([f.eta for f in fits], axis=1),
                    etaerr=jnp.stack([f.etaerr for f in fits], axis=1),
                    etaerr2=jnp.stack([f.etaerr2 for f in fits], axis=1),
                    lamsteps=config.lamsteps,
                    profile_eta=jnp.stack(
                        [f.profile_eta for f in fits]),
                    profile_power=jnp.stack(
                        [f.profile_power for f in fits], axis=1))

            return multi
        rc = _resolve_arc_scrunch(config, mesh, batch_shape,
                                  itemsize=itemsize)
        return make_arc_fitter(
            fdop=fdop, yaxis=yaxis_fit, tdel=tdel_fit,
            freq=fc, lamsteps=config.lamsteps, method=config.arc_method,
            numsteps=config.arc_numsteps,
            startbin=config.arc_startbin, cutmid=config.arc_cutmid,
            nsmooth=config.arc_nsmooth, delmax=arc_delmax,
            constraint=config.arc_constraint, ref_freq=config.ref_freq,
            asymm=config.arc_asymm, constraints=config.arc_brackets,
            scrunch_rows=rc, arc_tail=config.arc_tail)

    if config.split_programs:
        if jax.process_count() > 1:
            raise ValueError(
                "PipelineConfig.split_programs is not supported under a "
                "multi-process runtime yet (the handoff intermediates "
                "are process-local); drop the split on multihost pods")
        from .. import buckets as buckets_mod
        from ..fit.scint_fit import scint_cat_front, scint_cat_statics

        # canonicalised intermediate dims (the back unit's signature)
        dims: dict = {}
        aux: dict = {}
        if config.fit_scint:
            # acf_cuts_direct returns (cut_t [..., nt], cut_f [..., nf])
            rung = buckets_mod.vector_rung(nsub + nchan)
            dims["scint_rung"] = rung
            aux.update(scint_cat_statics(nsub, nchan, rung))
        if config.fit_arc:
            # grid inputs of the axes-free measurer: built by the SAME
            # fitter closure the fused path bakes them from (scrunch
            # route pinned to 0 here — the statics do not depend on it
            # and resolving the real route probes the device, which a
            # pipeline BUILD must never do)
            stat_fitter = make_arc_fitter(
                fdop=fdop, yaxis=yaxis_fit, tdel=tdel_fit, freq=fc,
                lamsteps=config.lamsteps, method=config.arc_method,
                numsteps=config.arc_numsteps,
                startbin=config.arc_startbin, cutmid=config.arc_cutmid,
                nsmooth=config.arc_nsmooth, delmax=arc_delmax,
                constraint=config.arc_constraint,
                ref_freq=config.ref_freq, asymm=config.arc_asymm,
                constraints=config.arc_brackets, scrunch_rows=0,
                arc_tail=config.arc_tail)
            mi = stat_fitter.measure_inputs
            # canonical runtime dtypes (what jit would canonicalise the
            # f64 statics to anyway — f32 under the production x64-off
            # runtime, f64 under x64 — and what a deserialized unit
            # executable demands exactly; the fused path bakes the SAME
            # canonicalised values as trace constants)
            grid_dt = jax.dtypes.canonicalize_dtype(np.float64)  # host-f64: canonicalisation probe
            aux["arc_eta"] = np.asarray(mi["arc_eta"], dtype=grid_dt)
            aux["arc_keep"] = np.asarray(mi["arc_keep"], dtype=bool)
            aux["arc_cmasks"] = np.asarray(mi["arc_cmasks"], dtype=bool)
            dims["arc_profile"] = int(config.arc_numsteps)
            dims["arc_grid"] = int(aux["arc_eta"].shape[0])
            dims["arc_windows"] = int(aux["arc_cmasks"].shape[0])
            assert dims["arc_grid"] == _split_profile_len(
                config.arc_numsteps)

        def front(dyn_batch):
            dyn_batch = jnp.asarray(dyn_batch)
            if gen_fn is not None:
                dyn_batch = gen_fn(dyn_batch)
            elif config.precision == "bf16_io":
                dyn_batch = dyn_batch.astype(jnp.float32)
            parts = {}
            if config.fit_scint:
                from ..ops.acf import acf_cuts_direct

                cut_t, cut_f = acf_cuts_direct(
                    dyn_batch, backend="jax",
                    method=_resolve_cuts(
                        config.scint_cuts, mesh, dyn_batch.shape,
                        itemsize=dyn_batch.dtype.itemsize),
                    lens=acf_lens)
                parts.update(scint_cat_front(cut_t, cut_f, dt, df,
                                             dims["scint_rung"]))
            if config.fit_arc:
                fft_in = (jnp.einsum("lf,bft->blt", jnp.asarray(W_np),
                                     dyn_batch)
                          if config.lamsteps else dyn_batch)
                sec_b = sspec_op(fft_in, prewhite=config.prewhite,
                                 window=config.window,
                                 window_frac=config.window_frac,
                                 db=True, backend="jax",
                                 lens=config.fft_lens,
                                 crop_rows=crop_rows,
                                 fused=config.fused_sspec)
                fitter = build_arc_fitter(tuple(dyn_batch.shape),
                                          dyn_batch.dtype.itemsize)
                prof, noise = jax.vmap(fitter.profile_of)(sec_b)
                parts["prof"] = prof
                parts["noise"] = noise
            return parts

        fkw = {}
        if donate:
            fkw["donate_argnums"] = 0
        if mesh is not None:
            fkw["in_shardings"] = mesh_mod.data_sharding(
                mesh, chan_sharded=chan_sharded)
        front_jit = jax.jit(front, **fkw)
        back_desc = split_backend_desc(config)
        back_jit = _make_split_backend(back_desc)
        fdop_np = np.asarray(fdop, dtype=np.float32)
        tdel_np = np.asarray(tdel, dtype=np.float32)
        beta_np = None if beta is None else np.asarray(beta,
                                                       dtype=np.float32)

        def result_fn(out):
            return PipelineResult(scint=out["scint"], arc=out["arc"],
                                  fdop=fdop_np, tdel=tdel_np,
                                  beta=beta_np)

        split_step = _SplitStep(front_jit, back_jit, aux, result_fn,
                                back_desc,
                                (freqs, times, config, mesh, chan_sharded,
                                 bool(donate), synth), dims)
        # geometry the streaming plane's incremental front
        # (stream/incremental.SlidingSspec) needs to rebuild this exact
        # front's transform chain as a sliding-window update — inert
        # for every other consumer
        split_step.inc_geom = {
            "config": config, "nf": nchan, "nf_s": nf_s, "W_np": W_np,
            "dt": dt, "df": df, "crop_rows": crop_rows, "dims": dims,
            "build_arc_fitter": build_arc_fitter,
        }
        return split_step

    def step(dyn_batch):
        dyn_batch = jnp.asarray(dyn_batch)
        if gen_fn is not None:
            # zero-H2D synthetic route: the staged input is the uint32
            # key batch [B, 2+F]; the dynspec batch is generated HERE,
            # inside the same compiled program, and never leaves HBM
            dyn_batch = gen_fn(dyn_batch)
        elif config.precision == "bf16_io":
            # bf16 is the TRANSFER/RESIDENCY dtype only: upcast at the
            # step's top so every FFT, matmul and accumulation below
            # runs in f32 (XLA fuses the convert into the first
            # consumers — the bf16 batch is read once, at half the
            # f32 bytes)
            dyn_batch = dyn_batch.astype(jnp.float32)
        out = {}
        scint = None
        scint2d = tilt = tilterr = None
        if config.fit_scint or config.return_acf or config.fit_scint_2d:
            dyn_acf = dyn_batch
            if mesh is not None and chan_sharded:
                # Sharding policy: the ACF/fit path is small (one [2nf,2nt]
                # array per epoch), so gather the channel axis and run it
                # purely data-parallel; only the big secondary-spectrum FFT
                # keeps the chan sharding.  (Also sidesteps an XLA CPU
                # fft-thunk layout RET_CHECK on chan-sharded ifft2.)
                from jax.sharding import NamedSharding, PartitionSpec as P

                dyn_acf = jax.lax.with_sharding_constraint(
                    dyn_batch, NamedSharding(mesh, P(mesh_mod.DATA_AXIS)))
            if config.return_acf or config.fit_scint_2d:
                acf_b = acf_op(dyn_acf, backend="jax", lens=acf_lens)
                if config.fit_scint:
                    scint = fit_scint_params_batch(
                        acf_b, dt, df, nchan, nsub, alpha=config.alpha,
                        steps=config.lm_steps)
                if config.fit_scint_2d:
                    from ..fit.scint_fit import fit_scint_params_2d_batch

                    scint2d, tilt, tilterr = fit_scint_params_2d_batch(
                        acf_b, dt, abs(df), nchan, nsub,
                        alpha=config.alpha, steps=config.lm_steps)
                if config.return_acf:
                    out["acf"] = acf_b
            elif config.fit_scint:
                # fast path: 1-D cuts via padded 1-D FFT reductions — same
                # values as the 2-D ACF route without materialising
                # [B, 2nf, 2nt] (ops.acf.acf_cuts_direct)
                from ..fit.scint_fit import fit_scint_params_from_dyn

                scint = fit_scint_params_from_dyn(
                    dyn_acf, dt, df, alpha=config.alpha,
                    steps=config.lm_steps,
                    cuts_method=_resolve_cuts(
                        config.scint_cuts, mesh, dyn_acf.shape,
                        itemsize=dyn_acf.dtype.itemsize),
                    acf_lens=acf_lens)
        arc = None
        arc_stacked = None
        sec_b = None
        if config.fit_arc or config.return_sspec:
            fft_in = (jnp.einsum("lf,bft->blt", jnp.asarray(W_np),
                                 dyn_batch)
                      if config.lamsteps else dyn_batch)
            sec_b = sspec_op(fft_in, prewhite=config.prewhite,
                             window=config.window,
                             window_frac=config.window_frac, db=True,
                             backend="jax", lens=config.fft_lens,
                             crop_rows=crop_rows,
                             fused=config.fused_sspec)
            if config.fit_arc:
                fitter = build_arc_fitter(tuple(dyn_batch.shape),
                                          dyn_batch.dtype.itemsize)
                arc = fitter(sec_b)
                if config.arc_stack:
                    # campaign stack: NaN pad-lanes/corrupted epochs
                    # drop out of the nan-robust reductions
                    arc_stacked = fitter.stacked(sec_b)
        return PipelineResult(
            scint=scint, arc=arc, acf=out.get("acf"),
            sspec=sec_b if config.return_sspec else None,
            fdop=jnp.asarray(fdop), tdel=jnp.asarray(tdel),
            beta=None if beta is None else jnp.asarray(beta),
            scint2d=scint2d, tilt=tilt, tilterr=tilterr,
            arc_stacked=arc_stacked)

    kw = {}
    if donate:
        kw["donate_argnums"] = 0
    if mesh is None:
        return jax.jit(step, **kw)

    in_shard = mesh_mod.data_sharding(mesh, chan_sharded=chan_sharded)
    if jax.process_count() > 1:
        # multihost: replicate outputs inside the compiled program (an
        # ICI/DCN all-gather) so every process can materialise full
        # results as numpy — jax forbids host-side conversion of
        # non-addressable shards
        kw["out_shardings"] = mesh_mod.replicated(mesh)
    return jax.jit(step, in_shardings=in_shard, **kw)


def stage_dtype(precision: str):
    """The host staging/transfer dtype of a pipeline batch — the single
    source of truth for run_pipeline's staging conversion and the
    warmup planner's signature dtypes (compile_cache.plan_steps), which
    must agree or a warmed artifact misses its key.

    ``"f32"`` keeps the historical staging dtype (float64 host arrays;
    jax canonicalises to f32 on transfer under the production x64-off
    runtime — bit-identical to every prior round).  ``"bf16_io"``
    converts host-side to bfloat16, so the H2D transfer and HBM
    residency run at 2 bytes/element."""
    if precision == "bf16_io":
        import ml_dtypes  # jax's own dtype-extension dependency

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(np.float64)  # host-f64: canonicalised on transfer


def transfer_nbytes(arr) -> int:
    """Bytes this staged batch actually moves H2D: element count times
    the CANONICALIZED itemsize.  The f32 policy stages float64
    host-side but jax downcasts it to f32 on transfer under the
    production x64-off runtime, so counting the staged ``arr.nbytes``
    would double-report the real traffic — and make bf16_io's
    documented "half an f32 transfer" read as a 4x counter drop.
    bfloat16 canonicalises to itself, so the bf16_io count is exact
    either way."""
    import jax

    return int(arr.size
               * np.dtype(jax.dtypes.canonicalize_dtype(arr.dtype)).itemsize)


def _resolve_donate(async_exec: bool, chunked: bool, mesh) -> bool:
    """Input-donation rule — single source of truth for run_pipeline and
    the warmup CLI (the AOT cache key includes donation, so the two must
    agree).  Donate only on the async chunked path (each chunk is a
    fresh buffer) and only on TPU (CPU ignores donation with a
    warning)."""
    return bool(async_exec and chunked and _target_is_tpu(mesh))


def _as_global_batch(dyn, mesh, chan_sharded: bool, commit: bool = False):
    """Under a multi-process runtime, assemble the (host-replicated)
    batch into a global jax.Array: each process contributes exactly its
    addressable shards by global index.  Single-process: pass through
    (jit's in_shardings handles the device_put), unless ``commit=True``
    — then the transfer happens HERE (with the mesh sharding applied),
    which the async executor uses to move H2D onto the prefetch thread
    and the AOT-loaded step requires (a deserialized export demands
    correctly-placed inputs).  ``chan_sharded`` is the already-resolved
    bool (_resolve_chan_sharded)."""
    import jax

    if mesh is not None and jax.process_count() > 1:
        sh = mesh_mod.data_sharding(mesh, chan_sharded=chan_sharded)
        return jax.make_array_from_callback(dyn.shape, sh,
                                            lambda idx: dyn[idx])
    if not commit:
        return dyn
    if mesh is not None:
        return jax.device_put(
            dyn, mesh_mod.data_sharding(mesh, chan_sharded=chan_sharded))
    return jax.device_put(dyn)


def run_pipeline(epochs=None, config: PipelineConfig = PipelineConfig(),
                 mesh=None, chunk: int | None = None,
                 chan_sharded: bool | None = None,
                 async_exec: bool = True, pad_chunks: bool = False,
                 pad_to: int | None = None, bucket: bool = False,
                 synthetic=None):
    """Host-side convenience driver: bucket heterogeneous epochs by shape,
    pad each bucket to the mesh's data-axis multiple, run the jit'd step
    per bucket (optionally in memory-bounded chunks), and gather results
    with invalid lanes dropped.  ``chan_sharded=None`` derives channel
    sharding from the mesh (any >1 ``chan`` axis shards the big
    secondary-spectrum FFT; see make_pipeline).

    ``async_exec`` (default on) overlaps host staging with device
    execution on the chunked path: a prefetch thread slices, pads and
    transfers chunk k+1 while the device runs chunk k (bounded queue,
    depth 2 — parallel.schedule); the sync path (``async_exec=False``)
    is preserved and bit-identical (tested).  ``pad_chunks`` pads the
    final uneven chunk up to the chunk size with mask-invalid lanes
    (sliced off at gather, like divisibility pads), so a chunked survey
    compiles exactly ONE program instead of two.

    ``pad_to`` pads every bucket whose batch is SMALLER than that size
    up to exactly ``pad_to`` epochs with the same mask-invalid lanes —
    the resident-service contract (scintools_tpu.serve): a partial
    dynamic batch executes the one warm compiled signature the batcher
    targets instead of tracing a fresh program per fill level.  Must be
    a multiple of the mesh's data-axis size; buckets already at or over
    ``pad_to`` are left alone (the chunk machinery governs them).

    ``bucket`` canonicalises every shape bucket onto the CLOSED batch
    ladder (scintools_tpu.buckets): the batch pads up to the nearest
    catalog rung (``pad_to`` semantics), or — above the top rung —
    chunks at the top rung with uniform-chunk padding, so a survey of
    ANY epoch count executes only catalog signatures (the ones
    ``warmup --catalog`` pre-compiled).  Real-lane results stay
    byte-identical to the unbucketed run (the same mask-invalid-lane
    machinery).  An explicit ``chunk`` bounds the ladder's top rung
    (device-memory cap); mutually exclusive with ``pad_to``.  Per-
    catalog-entry fill is observable: ``bucket_hits[...]`` /
    ``bucket_lanes_real[...]`` / ``bucket_lanes_pad[...]`` counters and
    ``bucket_catalog[...]`` gauges feed ``trace report``'s
    shape-bucket catalog section (pad-waste per bucket).

    When the persistent compile cache is enabled (``SCINT_COMPILE_CACHE``,
    on by default — scintools_tpu.compile_cache) each step signature is
    first looked up as an AOT artifact written by ``scintools-tpu
    warmup``: a hit deserializes the step instead of re-tracing it
    (``compile_cache_hit``/``compile_cache_miss`` counters), so a warmed
    fresh process re-traces nothing (``jit_cache_miss == 0``).

    Returns a list of (indices, PipelineResult) per bucket, where
    ``indices`` maps result lanes back to the input epoch order: lane k of
    every [B]-leading result leaf is epoch ``indices[k]`` (divisibility
    pad-lanes are sliced off before returning).

    ``synthetic`` (a :class:`scintools_tpu.sim.campaign.SynthSpec`, in
    place of ``epochs``) runs the ZERO-H2D on-device campaign route:
    the staged input is the campaign's uint32 key batch ``[B, 2+F]``
    (``campaign.stage_batch``) and the compiled step generates the
    dynspec batch in HBM before the analysis stages — ``bytes_h2d`` is
    O(keys), independent of (nf, nt), counter-asserted in tier-1.  The
    key batch rides the SAME machinery as a staged dynspec batch: mesh
    data-axis sharding, divisibility/rung padding (pad lanes repeat the
    last key row — a re-simulation, sliced off at gather), chunking,
    bucket-catalog canonicalisation, and compile-cache/AOT artifacts
    (the spec's generator identity is part of the step key).  Not
    supported with ``bf16_io`` precision (nothing to transfer),
    ``arc_stack`` (pad lanes cannot be NaN-filled) or a chan-sharded
    mesh.  ``epochs_synthesized`` counts the generated epochs.

    When :mod:`scintools_tpu.obs` tracing is enabled, each bucket batch
    records the stage spans ``pipeline.stage`` (host staging: bucketing,
    padding, step build), ``pipeline.step.compile`` /
    ``pipeline.step.execute`` (the fused sspec→arc-fit device step, with
    compile time split from fenced execute time per input signature),
    ``pipeline.prefetch`` (async host staging per chunk) and
    ``pipeline.gather`` (result slicing to host), under one
    ``pipeline.run`` root, plus ``epochs_processed`` / ``bytes_h2d`` /
    ``jit_cache_miss`` / ``prefetch_stall_s`` counters.  Disabled
    tracing takes the identical dispatch path (tests assert bit-identical
    results on vs off).
    """
    from .. import compile_cache
    from .batch import pad_batch
    from .schedule import execute_chunks

    if synthetic is None and epochs is None:
        raise TypeError("run_pipeline needs epochs (file route) or "
                        "synthetic= (on-device campaign route)")
    genid = campaign = None
    if synthetic is not None:
        if epochs:
            raise ValueError("pass epochs OR synthetic=, not both (a "
                             "campaign generates its own epochs "
                             "on-device)")
        from ..sim import campaign

        campaign.validate_spec(synthetic)
        _validate_synth_config(config, mesh, chan_sharded)
        genid = campaign.generator_id(synthetic)

    multiple = 1
    if mesh is not None:
        multiple = mesh.shape[mesh_mod.DATA_AXIS]
    if pad_to is not None and (pad_to < 1 or pad_to % multiple):
        raise ValueError(
            f"pad_to={pad_to} must be a positive multiple of the mesh's "
            f"data-axis size ({multiple}) — the padded batch is the "
            "compiled signature")
    if bucket and pad_to is not None:
        raise ValueError(
            "bucket=True canonicalises each shape bucket's batch onto "
            "the catalog ladder itself; it is mutually exclusive with "
            "an explicit pad_to")
    if bucket:
        from .. import buckets as buckets_mod
    chan_sharded = _resolve_chan_sharded(mesh, chan_sharded)
    use_cache = compile_cache.cache_dir() is not None
    if use_cache:
        compile_cache.enable_persistent_cache()
        if obs.enabled():
            man = compile_cache.artifact_manifest()
            if man is not None:
                # provenance: this run's persistent cache came from an
                # unpacked warm-cache artifact (trace report shows it)
                obs.gauge("compile_cache_artifact",
                          str(man.get("digest", "?")))
    n_total = (synthetic.n_epochs if synthetic is not None
               else len(epochs))
    buckets_iter = ([list(range(synthetic.n_epochs))]
                    if synthetic is not None
                    else _bucket_epochs(epochs).values())
    results = []
    with obs.span("pipeline.run", epochs=n_total):
        # survey-start memory sample (obs/devmem): the HBM gauges and
        # one streamed timeline stamp exist even before the first
        # instrumented execute window — a trace of a run that OOMs in
        # staging still shows where memory stood.  No-op when
        # untraced or on backends without memory_stats().
        obs.devmem.sample(stream=True)
        for idx in buckets_iter:
            eff_pad_to, eff_chunk, eff_pad_chunks = pad_to, chunk, pad_chunks
            with obs.span("pipeline.stage", epochs=len(idx)) as stage_sp, \
                    trace_annotation("pipeline.stage"):
                if synthetic is not None:
                    # the staged batch is the key array: pad it to the
                    # mesh multiple by repeating the last row (a
                    # re-simulated lane, sliced off at gather exactly
                    # like a divisibility pad-lane of a file batch)
                    freqs_np, times_np = campaign.synth_axes(synthetic)
                    dyn = campaign.stage_batch(synthetic)
                    short = (-len(idx)) % multiple
                    if short:
                        dyn = np.concatenate(
                            [dyn, np.repeat(dyn[-1:], short, axis=0)],
                            axis=0)
                else:
                    group = [epochs[i] for i in idx]
                    batch, _mask = pad_batch(group, batch_multiple=multiple)
                    freqs_np = np.asarray(group[0].freqs)
                    times_np = np.asarray(group[0].times)
                    dyn = np.asarray(batch.dyn)
                if bucket:
                    # catalog canonicalisation: pad the (divisibility-
                    # padded) batch up to the nearest ladder rung, or
                    # chunk at the top rung with uniform-chunk padding
                    # — either way only CLOSED-catalog signatures
                    # execute.  An explicit ``chunk`` caps the ladder
                    # top: adjusted DOWN to a mesh multiple
                    # (_adjust_chunk), like the non-bucket path — a
                    # device-memory bound must never round up
                    top = (None if chunk is None
                           else _adjust_chunk(multiple, chunk))
                    plan = buckets_mod.bucket_plan(dyn.shape[0], multiple,
                                                   top=top)
                    eff_pad_to = plan.get("pad_to")
                    eff_chunk = plan.get("chunk")
                    eff_pad_chunks = plan.get("pad_chunks", False)
                if config.arc_stack and not np.all(_mask.epoch):
                    # divisibility pad-lanes are COPIES of the last epoch
                    # (pad_batch) — fine for per-epoch results (sliced off
                    # below) but they would bias the campaign stack;
                    # NaN-fill them so the stacked nanmean drops them
                    dyn = dyn.copy()
                    dyn[~_mask.epoch] = np.nan
                if eff_pad_to is not None and dyn.shape[0] < eff_pad_to:
                    # fixed-signature padding: extend to exactly pad_to
                    # with mask-invalid lanes (copies of the last epoch;
                    # NaN under arc_stack so the campaign nanmean drops
                    # them), sliced off at gather like divisibility pads
                    extra = np.repeat(dyn[-1:], eff_pad_to - dyn.shape[0],
                                      axis=0)
                    if config.arc_stack:
                        extra = np.full_like(extra, np.nan)
                    dyn = np.concatenate([dyn, extra], axis=0)
                c = None
                if eff_chunk is not None and eff_chunk < dyn.shape[0]:
                    # memory-bounded chunking; chunk must respect mesh
                    # divisibility
                    c = _adjust_chunk(multiple, eff_chunk)
                    if c != eff_chunk:
                        import warnings

                        warnings.warn(
                            f"run_pipeline: chunk={eff_chunk} adjusted to "
                            f"{c} (the mesh's data axis needs multiples of "
                            f"{multiple}); size chunk accordingly when "
                            "bounding device memory", stacklevel=2)
                    if eff_pad_chunks and dyn.shape[0] % c:
                        # uniform-chunk padding: extend the final chunk to
                        # the full chunk size with mask-invalid lanes —
                        # the same pad-lane machinery as divisibility
                        # padding (copies of the last epoch, NaN under
                        # arc_stack so the campaign nanmean drops them),
                        # sliced off at gather.  One chunk size -> ONE
                        # compiled program for the whole survey.
                        pad_n = c - dyn.shape[0] % c
                        extra = np.repeat(dyn[-1:], pad_n, axis=0)
                        if config.arc_stack:
                            extra = np.full_like(extra, np.nan)
                        dyn = np.concatenate([dyn, extra], axis=0)
                if synthetic is None:
                    sdt = stage_dtype(config.precision)
                    if dyn.dtype != sdt:
                        # precision policy conversion LAST (after every
                        # pad manipulation, which runs in f64): under
                        # bf16_io the transfer and HBM residency halve
                        # vs f32 — the step upcasts to f32 at its top
                        # for compute.  (The synthetic route's staged
                        # batch is the uint32 key array: no conversion.)
                        dyn = dyn.astype(sdt)
                donate = _resolve_donate(async_exec, c is not None, mesh)
                step = make_pipeline(freqs_np, times_np, config,
                                     mesh=mesh, chan_sharded=chan_sharded,
                                     donate=donate, synth=synthetic)
                stage_sp.set(batch_shape=list(dyn.shape),
                             stage_dtype=str(dyn.dtype))
            if bucket and obs.enabled():
                # catalog-fill accounting: the executed signature's hit
                # count and real-vs-padded lanes (pad-waste), plus one
                # existence gauge per ladder rung so `trace report` can
                # show unused catalog entries alongside the hit ones.
                # Synthetic buckets label with the ANALYSIS grid (the
                # staged key batch is [B, 2+F]) plus a :synth marker —
                # key-fed and file-fed signatures are different
                # programs and must not share a catalog row.
                sig_b = c if c is not None else dyn.shape[0]
                grid = (f"{dyn.shape[1]}x{dyn.shape[2]}"
                        if synthetic is None
                        else f"{len(freqs_np)}x{len(times_np)}:synth")
                label = f"{sig_b}x{grid}:{dyn.dtype}"
                obs.inc(f"bucket_hits[{label}]")
                obs.inc(f"bucket_lanes_real[{label}]", len(idx))
                obs.inc(f"bucket_lanes_pad[{label}]",
                        dyn.shape[0] - len(idx))
                for r in buckets_mod.batch_ladder(
                        multiple, top=None if chunk is None
                        else _adjust_chunk(multiple, chunk)):
                    obs.gauge(f"bucket_catalog[{r}x{grid}:{dyn.dtype}]",
                              1)
            obs.inc("epochs_processed", len(idx))
            if synthetic is not None:
                obs.inc("epochs_synthesized", len(idx))
            obs.inc("bytes_h2d", transfer_nbytes(dyn))
            # fixed-iteration LM budget actually dispatched for this
            # batch (host-side: trace-time counters inside the jit'd
            # step would undercount cached re-executions)
            n_lm_fits = int(config.fit_scint) + int(config.fit_scint_2d)
            if n_lm_fits:
                obs.inc("lm_steps",
                        config.lm_steps * n_lm_fits * dyn.shape[0])
            split_step = step if isinstance(step, _SplitStep) else None
            if split_step is not None:
                # per-unit accounting: compile/execute spans and
                # compile_ms / jit_cache_miss series land under
                # pipeline.front / pipeline.back (the back wrapper is
                # SHARED across axes, so a novel shape whose
                # intermediates hit warm rungs counts zero back misses)
                step = split_step.instrumented()
            else:
                step = obs.instrument_jit(step, "pipeline.step")
            B = dyn.shape[0]
            # AOT lookup: one artifact per step batch size this bucket
            # will issue (warmup wrote them keyed identically); split
            # steps look up each UNIT's artifact and compose, falling
            # back unit-wise to the live jit
            aot = {}
            if use_cache:
                for b in sorted(_step_batch_sizes(B, multiple, c,
                                                  pad_chunks=eff_pad_chunks)):
                    if split_step is not None:
                        ffn = compile_cache.load_step(
                            split_step.front_key((b,) + dyn.shape[1:],
                                                 dyn.dtype))
                        bfn = (compile_cache.load_step(
                            split_step.back_key(b))
                            if split_step.back_aot_eligible() else None)
                        if ffn is not None or bfn is not None:
                            aot[b] = split_step.instrumented(
                                front_aot=ffn, back_aot=bfn)
                        continue
                    fn = compile_cache.load_step(compile_cache.step_key(
                        freqs_np, times_np, config, mesh, chan_sharded,
                        (b,) + dyn.shape[1:], dyn.dtype, donate=donate,
                        synth=genid))
                    if fn is not None:
                        aot[b] = obs.instrument_jit(fn, "pipeline.step",
                                                    aot=True)

            def dispatch(x, _aot=aot, _step=step):
                # chaos site: a deterministic RESOURCE_EXHAUSTED here
                # drives the chunked path's OOM-adaptive backoff
                faults.check("driver.chunk_execute")
                # labeled device timeline: the xprof trace (--xprof)
                # shows each step dispatch as a named region
                with trace_annotation("pipeline.step"):
                    fn = _aot.get(int(x.shape[0]))
                    if fn is None:
                        return _step(x)
                    if isinstance(x, np.ndarray):
                        # a deserialized export needs correctly-placed
                        # inputs (it has no in_shardings to do it)
                        x = _as_global_batch(x, mesh, chan_sharded,
                                             commit=True)
                    return fn(x)

            if c is None:
                res = dispatch(_as_global_batch(dyn, mesh, chan_sharded))
            else:
                parts = _run_chunked_adaptive(
                    dispatch, dyn, B, c, multiple, mesh, chan_sharded,
                    async_exec, execute_chunks)
                res = _concat_results(parts)
            with obs.span("pipeline.gather", epochs=len(idx)), \
                    trace_annotation("pipeline.gather"):
                results.append((np.asarray(idx),
                                _take_lanes(res, len(idx), B)))
    return results


def _admit_chunk(dyn, c: int, multiple: int) -> int:
    """Predictive OOM avoidance (ISSUE 12): before launching a chunk
    round, compare the signature's predicted peak HBM against its
    measured budget and step the chunk DOWN — halved, mesh-floored,
    the same rule as the reactive backoff, so bucket-ladder rungs step
    onto rungs — until the prediction fits.  Prediction trust order
    (:func:`obs.devmem.predicted_peak`): an exact recorded
    execute-window peak, a batch-scaled one, the ``step_bytes``
    cost-analysis model, a lower-bound window estimate; a
    never-profiled signature falls back to the chunk's own staged
    input bytes (a chunk cannot run without its input resident).
    Absolute sources (recorded peaks) compare against ``bytes_limit``;
    incremental ones (model, input bytes) against live headroom.  Each
    step-down counts ``oom_predicted_avoided`` and re-points
    ``effective_chunk``; the reactive ``RESOURCE_EXHAUSTED`` halving
    stays the fallback for a prediction that was wrong.

    The ``driver.admit_chunk`` chaos site (kind="oom") forces a
    marginal-headroom reading — each fire takes exactly ONE predictive
    step-down — so tier-1 proves the path without a real OOM, with
    results byte-identical (chunking only partitions the batch axis).
    On backends without ``memory_stats()`` (CPU) headroom is None and
    admission is a no-op."""
    from ..obs import devmem
    from ..utils.log import get_logger, log_event

    def step_down(cur: int, pred, budget) -> int:
        new_c = _adjust_chunk(multiple, max(cur // 2, 1))
        if new_c >= cur:
            return cur       # at the floor: launch and let the
        #                      reactive backoff be the judge
        obs.inc("oom_predicted_avoided")
        obs.gauge("effective_chunk", new_c)
        log_event(get_logger(), "oom_predicted_avoided", chunk=cur,
                  new_chunk=new_c, predicted_bytes=round(pred[0]),
                  predicted_source=pred[1], budget_bytes=round(budget))
        return new_c

    def predict(cur: int):
        pred = devmem.predicted_peak("pipeline.step", cur,
                                     tuple(int(s)
                                           for s in dyn.shape[1:]))
        if pred is None:
            # residency lower bound: the chunk's own staged input
            pred = (float(transfer_nbytes(dyn[:cur])), "input-bytes")
        return pred

    try:
        faults.check("driver.admit_chunk")
    except Exception as e:
        if not faults.is_oom_error(e):
            raise
        # injected marginal-headroom reading: one step-down per fire
        return step_down(c, predict(c), 0.0)
    snap = devmem.snapshot()
    if snap is None or not snap["bytes_limit"]:
        return c
    limit = float(snap["bytes_limit"])
    headroom = limit - float(snap["bytes_in_use"])
    while c > multiple:
        pred = predict(c)
        # unit discipline: measured window peaks are ABSOLUTE residency
        # totals (ambient allocations included when they were read) —
        # compare those against the limit; the model / input-bytes
        # sources count only what the chunk ADDS — compare against
        # headroom.  Mixing them double-counts the already-resident
        # bytes and would spuriously shrink any pipeline whose working
        # set passes half of HBM.
        budget = (limit if pred[1] in devmem.ABSOLUTE_PEAK_SOURCES
                  else headroom)
        if pred[0] <= budget:
            break
        new_c = step_down(c, pred, budget)
        if new_c >= c:
            break
        c = new_c
    return c


def _run_chunked_adaptive(dispatch, dyn, B: int, chunk: int,
                          multiple: int, mesh, chan_sharded: bool,
                          async_exec: bool, execute_chunks) -> list:
    """The chunk loop with OOM-adaptive backoff: execute ``dyn`` in
    ``chunk``-sized steps; on a device RESOURCE_EXHAUSTED (a real
    ``XlaRuntimeError`` or an injected ``driver.chunk_execute`` fault)
    HALVE the chunk size — floored at the mesh data-axis multiple —
    and replay only the epochs whose chunks had not completed.  The
    async prefetcher is already drained/joined by ``execute_chunks``
    before the exception propagates, so each retry round starts a
    fresh producer against the new chunk signature.

    Self-healing is observable: each backoff increments ``oom_backoff``
    and re-points the ``effective_chunk`` gauge at the surviving size,
    so ``trace report`` shows the degradation (docs/reliability.md).
    A survey that completes after backoff returns results identical to
    the un-faulted run — chunk decomposition only partitions the batch
    axis, and every per-epoch measurement is lane-independent (asserted
    by tests/test_faults.py byte-for-byte on the exported CSV).

    Errors surfacing at dispatch/compile time are caught per chunk (the
    dominant oversized-chunk failure: XLA allocates device memory when
    the executable loads); an OOM first surfacing at gather time aborts
    the bucket as before — un-fenced async dispatch cannot attribute it
    to a chunk.

    Returns the per-chunk PipelineResult list in epoch order.
    """
    from ..utils.log import get_logger, log_event

    parts: list = []
    pos, c = 0, chunk
    while pos < B:
        # predictive admission (ISSUE 12): step the chunk rung down
        # BEFORE launching anything the measured headroom says would
        # OOM; the reactive halving below stays the fallback
        c = _admit_chunk(dyn, c, multiple)
        starts = list(range(pos, B, c))

        def stage_chunk(k, _dyn=dyn, _starts=starts, _c=c):
            i = _starts[k]
            # commit on the async path: H2D runs on the
            # prefetch thread, overlapped with device compute
            return _as_global_batch(_dyn[i:i + _c], mesh,
                                    chan_sharded,
                                    commit=async_exec)

        done: list = []
        try:
            execute_chunks(dispatch, len(starts), stage_chunk,
                           async_exec=async_exec, out=done)
            parts.extend(done)
            return parts
        except Exception as e:
            if not faults.is_oom_error(e):
                raise
            new_c = _adjust_chunk(multiple, max(c // 2, 1))
            if new_c >= c:
                # already at the mesh-multiple floor: genuinely too
                # big for this device — nothing left to adapt
                raise
            # keep the completed prefix; replay from the first chunk
            # that did not finish, at the halved size
            parts.extend(done)
            pos = starts[len(done)]
            obs.inc("oom_backoff")
            obs.gauge("effective_chunk", new_c)
            log_event(get_logger(), "oom_backoff", chunk=c,
                      new_chunk=new_c, resume_epoch=pos,
                      remaining=B - pos, error=repr(e))
            c = new_c
    return parts


def _take_lanes(res: PipelineResult, n: int, B: int) -> PipelineResult:
    """Slice divisibility pad-lanes off every [B]-leading result leaf.

    Leaves are pulled to HOST before slicing: an eager ``x[:n]`` on an
    array sharded over the mesh compiles a resharding program whose
    cross-module all-gather must rendezvous ALL devices' threads — on an
    oversubscribed host (1 core, 8 virtual devices) stragglers can miss
    XLA's 40 s rendezvous budget and the runtime CHECK-aborts the whole
    process (observed as the round-4 full-suite flake).  A host transfer
    is collective-free, and every consumer reads these lanes as numpy
    anyway."""
    if n == B:
        return res
    import jax

    def slice_leaf(x):
        return np.asarray(x)[:n] if (hasattr(x, "ndim")
                                     and x.ndim >= 1) else x

    def take(val):
        if val is None:
            return None
        return jax.tree_util.tree_map(slice_leaf, val)

    arc = res.arc
    if arc is not None:
        # every arc leaf is [B]-leading except the shared profile_eta grid
        arc = dataclasses.replace(take(dataclasses.replace(
            arc, profile_eta=None)), profile_eta=arc.profile_eta)
    return dataclasses.replace(
        res, scint=take(res.scint), arc=arc, acf=take(res.acf),
        sspec=take(res.sspec), scint2d=take(res.scint2d),
        tilt=take(res.tilt), tilterr=take(res.tilterr))


def _concat_results(parts):
    """Concatenate PipelineResult chunks along the epoch axis ([B]-leading
    leaves of scint/arc/acf/sspec); grid axes are identical across chunks."""
    import jax

    def _cat_leaf(*xs):
        a = np.asarray(xs[0])
        if a.ndim == 0:  # shared scalar (e.g. fixed talpha)
            return a
        return np.concatenate([np.asarray(x) for x in xs], axis=0)

    def cat(field):
        vals = [getattr(p, field) for p in parts]
        if vals[0] is None:
            return None
        return jax.tree_util.tree_map(_cat_leaf, *vals)

    first = parts[0]
    out = {f: cat(f) for f in ("scint", "acf", "sspec", "scint2d", "tilt",
                               "tilterr")}
    arc = None
    if first.arc is not None:
        # profile_eta is a shared grid (no batch axis); splice it back
        cat_arc = jax.tree_util.tree_map(
            _cat_leaf,
            *[dataclasses.replace(p.arc, profile_eta=None) for p in parts])
        arc = dataclasses.replace(cat_arc,
                                  profile_eta=np.asarray(first.arc.profile_eta))
    arc_stacked = None
    if first.arc_stacked is not None:
        if len(parts) == 1:
            arc_stacked = first.arc_stacked
        else:
            # the campaign stack is a per-STEP reduction: a chunked run
            # yields one sub-campaign fit per chunk, stacked to
            # [n_chunks] leaves (consumers see ndim>=1 and report
            # each).  profile_eta is the SHARED grid — splice it back
            # unstacked, as the per-epoch arc concat above does.
            arc_stacked = jax.tree_util.tree_map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]),
                *[dataclasses.replace(p.arc_stacked, profile_eta=None)
                  for p in parts])
            arc_stacked = dataclasses.replace(
                arc_stacked,
                profile_eta=np.asarray(first.arc_stacked.profile_eta))
    return PipelineResult(scint=out["scint"], arc=arc, acf=out["acf"],
                          sspec=out["sspec"], fdop=np.asarray(first.fdop),
                          tdel=np.asarray(first.tdel),
                          beta=None if first.beta is None
                          else np.asarray(first.beta),
                          scint2d=out["scint2d"], tilt=out["tilt"],
                          tilterr=out["tilterr"],
                          arc_stacked=arc_stacked)
