"""Multi-host runtime bootstrap + cross-device survey statistics.

The reference is strictly single-process (SURVEY.md §2.7); its "survey
statistics" are per-file CSV rows aggregated by hand.  This module holds
the two pieces that make the framework a distributed system:

* :func:`initialize_multihost` — one-call ``jax.distributed`` bootstrap
  (coordinator + process grid from args or the standard env vars).  After
  it, ``jax.devices()`` enumerates the global device set and the
  existing ``make_mesh``/``make_pipeline`` code scales unchanged: the
  ``data`` axis spans hosts (DCN carries only data-parallel traffic),
  ``chan`` stays intra-host on ICI.
* :func:`make_hybrid_mesh` — an ICI×DCN-aware mesh: devices grouped so
  the ``chan`` (ICI) axis never crosses a DCN boundary
  (``mesh_utils.create_hybrid_device_mesh``).
* :func:`survey_stats` — masked mean/std/count reductions of per-epoch
  measurements (tau, dnu, eta, ...) over a data-sharded batch with
  ``psum`` collectives inside ``shard_map`` — the "mean curvature per
  pulsar" reduction of SURVEY.md §2.7, running on ICI/DCN instead of a
  host gather.
"""

from __future__ import annotations

import os

from .mesh import CHAN_AXIS, DATA_AXIS, make_mesh


def initialize_multihost(coordinator_address: str | None = None,
                         num_processes: int | None = None,
                         process_id: int | None = None) -> bool:
    """Initialise the JAX distributed runtime for a multi-host slice.

    Arguments default to the standard env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID); on TPU pods all three can be
    auto-detected by jax and may stay None.  Returns True when a
    multi-process runtime was initialised, False for single-process runs
    (no-op).  Safe to call twice.
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None and (v := os.environ.get("JAX_NUM_PROCESSES")):
        num_processes = int(v)
    if process_id is None and (v := os.environ.get("JAX_PROCESS_ID")):
        process_id = int(v)
    if coordinator_address is None and num_processes in (None, 1):
        return False  # single host
    try:
        # CPU multi-process computations need a cross-process
        # collectives backend: on jaxlib 0.4.37 the default is 'none',
        # so any computation over a cross-process global array fails
        # with "Multiprocess computations aren't implemented on the
        # CPU backend" — the root cause of the test_multihost failures
        # the PR 3 port-retry deflake misattributed.  Wire gloo (the
        # only built-in) unless the user already chose one; the option
        # only affects the CPU client, so TPU pods are untouched.
        # Must run before initialize() creates the backend.  The value
        # is NOT exposed as a jax.config attribute on 0.4.37 —
        # config._read is the only readout (verified: attribute access
        # raises even after a successful update), hence the private
        # call; a user-set 'mpi' survives untouched.
        try:
            cur = jax.config._read("jax_cpu_collectives_implementation")
            if cur in (None, "", "none"):
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
        except Exception:  # fault-ok: option absent on this jax version
            pass
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    except RuntimeError as e:
        # idempotency: jax raises "distributed.initialize should only be
        # called once" (wording differs across versions — match both)
        msg = str(e).lower()
        if "already" not in msg and "once" not in msg:
            raise
    return True


def make_hybrid_mesh(ici_chan: int = 1, devices=None):
    """Mesh whose ``chan`` axis stays inside each ICI island.

    ``ici_chan`` is the channel-parallel degree per host/slice; the
    ``data`` axis takes everything else (spanning DCN between hosts).
    Falls back to a flat mesh when there is a single process.
    """
    import jax

    n_proc = getattr(jax, "process_count", lambda: 1)()
    devices = list(jax.devices()) if devices is None else list(devices)
    n = len(devices)
    if n % ici_chan:
        raise ValueError(f"{n} devices not divisible by ici_chan={ici_chan}")
    if n_proc <= 1:
        return make_mesh((n // ici_chan, ici_chan), devices=devices)
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    per_host = n // n_proc
    if ici_chan > per_host or per_host % ici_chan:
        raise ValueError(
            f"ici_chan={ici_chan} must divide the {per_host} devices per "
            f"host (chan must not span the DCN boundary)")
    try:
        dev_array = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(per_host // ici_chan, ici_chan),
            dcn_mesh_shape=(n_proc, 1), devices=devices)
    except ValueError:
        # devices without slice metadata (multi-process CPU meshes, some
        # single-slice topologies): group by process so the chan axis
        # still never crosses the process (DCN) boundary
        import numpy as _np

        ordered = sorted(devices, key=lambda d: (d.process_index, d.id))
        dev_array = _np.array(ordered, dtype=object).reshape(
            n // ici_chan, ici_chan)
    return Mesh(dev_array, (DATA_AXIS, CHAN_AXIS))


def survey_stats(values, mesh, valid=None, axis: str = DATA_AXIS) -> dict:
    """Masked survey statistics of a data-sharded [B] measurement array.

    Invalid lanes (padding, failed fits, NaNs) are excluded via ``valid``
    plus an automatic finiteness mask — the collective analogue of the
    reference's quarantine pattern (bad epochs never crash or bias the
    reduction).  All three reductions ride one ``psum`` each inside
    ``shard_map``; the result is replicated on every device/host.

    Returns {"mean", "std", "count"} as scalars.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    x = jnp.asarray(values)
    ok = jnp.isfinite(x)
    if valid is not None:
        ok = ok & jnp.asarray(valid)

    def local(xb, okb):
        # two-pass: global mean first, then centred second moment — avoids
        # the catastrophic E[x^2]-mean^2 cancellation in f32 when the
        # measurement has a large mean and small scatter (tau ~ 5000 s
        # +- 0.5 would otherwise round to std=0 on TPU)
        xb = jnp.where(okb, xb, 0.0)
        n = jax.lax.psum(jnp.sum(okb), axis_name=axis)
        nf = jnp.maximum(n, 1).astype(xb.dtype)
        mean = jax.lax.psum(jnp.sum(xb), axis_name=axis) / nf
        d = jnp.where(okb, xb - mean, 0.0)
        var = jax.lax.psum(jnp.sum(d * d), axis_name=axis) / nf
        return (mean[None], jnp.sqrt(var)[None], n[None])

    mean, std, count = shard_map(
        local, mesh=mesh, in_specs=(P(axis), P(axis)),
        out_specs=(P(None), P(None), P(None)))(x, ok)
    return {"mean": float(mean[0]), "std": float(std[0]),
            "count": int(count[0])}
