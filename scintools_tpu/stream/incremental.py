"""Incremental streaming hot path (ISSUE 17): O(hop) tick updates.

A stream tick re-fits the last ``W`` samples every ``hop`` new ones.
PR 15 made the tick a single warm compiled signature, but the program
still recomputes the whole ``[nf, W]`` window from scratch — the
secondary-spectrum FFT pair, the ACF cuts, a cold-started LM fit.
This module turns the between-resync ticks into O(hop) work:

* :class:`SlidingSspec` keeps the time-axis DFT of the sspec stage's
  PREWHITENED window as device-resident state ``S [Rs, ncfft]`` and
  advances it per hop with a rank-``hop`` update (two small GEMMs for
  the departing/arriving columns) instead of re-running the 2-D FFT.
  The decomposition that makes this exact: with the split edge taper
  ``tw``/``fw`` (flat ones in the interior) and the 2x2 second
  difference ``p`` of the windowed, mean-subtracted input,

      p = p_flat + p_edge,   p_flat[f, j] = vx[f, j+1] - vx[f, j],

  where ``vx`` is the frequency difference of the ROW-tapered raw
  window.  ``p_flat`` is column-local and shift-invariant (both global
  mean subtractions cancel in it), so its transform obeys a sliding
  DFT recurrence; ``p_edge`` (the taper slopes plus the mean term
  ``-m * dfw x dtw``) lives on the ~``window_frac*W`` edge columns and
  is recomputed fresh each tick from those columns only.

* :class:`IncrementalCuts` extends the :class:`~scintools_tpu.stream.
  ingest.IncrementalACF` machinery from the single zero-freq time-lag
  cut to the FULL cut pair the scint fitter consumes: raw pair sums
  over time lags 0..W-1 and freq lags 0..nf-1 slide in O(hop * W * nf)
  per push; the exact mean-centering (what ``acf_cuts_direct`` bakes
  into its transform) is applied at read time from the window's
  column/row sums, so the accumulator itself never needs re-centering.

Both carry the same drift-bounding discipline as ``IncrementalACF``:
float error from the add/subtract updates accumulates, so every
``resync_every`` ticks the session runs the FULL path (which is
byte-identical to the batch pipeline by the PR 14/15 contracts) and
rebuilds the incremental state from scratch — resync rows are exact,
between-resync rows sit within a test-pinned drift budget.
"""

from __future__ import annotations

import numpy as np

DEFAULT_RESYNC_EVERY = 16


class IncrementalCuts:
    """Both fitter ACF cuts over a sliding window, updated incrementally.

    Maintains the RAW pair sums (no mean subtraction)

        Rt[lag] = sum_{f, j} x[f, j] * x[f, j + lag]     lag = 0..W-1
        Rf[lag] = sum_{f, t} x[f, t] * x[f + lag, t]     lag = 0..nf-1

    over the ring's host mirror.  A push of ``c`` columns subtracts the
    evicted columns' pair terms and adds the new ones — one
    ``[c, nf] x [nf, W]`` GEMM per axis instead of the from-scratch
    ``[W, nf] x [nf, W]`` — with the same periodic exact resync as
    :class:`~scintools_tpu.stream.ingest.IncrementalACF`.  The
    mean-centred cuts the fitter consumes are derived at read time
    (:meth:`cuts`): with ``m`` the window mean and ``s``/``r`` the
    column/row sums,

        cut_t[lag] = Rt[lag] - m*(prefix_s[lag] + suffix_s[lag])
                     + nf*(W-lag)*m^2

    (and symmetrically for ``cut_f``) — an exact expansion of the
    mean-subtracted correlation, so the accumulator never has to be
    re-centred when the mean drifts.  Host float64 throughout: this is
    host bookkeeping, and the wide accumulator is what keeps the
    between-resync drift at FFT-rounding scale.
    """

    def __init__(self, window: int, nf: int,
                 resync_every: int = DEFAULT_RESYNC_EVERY * 4):
        self.window = int(window)
        self.nf = int(nf)
        if self.window < 2 or self.nf < 2:
            raise ValueError(f"IncrementalCuts: need window >= 2 and "
                             f"nf >= 2, got ({window}, {nf})")
        self.resync_every = int(resync_every)
        self.rt = np.zeros(self.window, dtype=np.float64)  # host-f64: accumulator precision
        self.rf = np.zeros(self.nf, dtype=np.float64)  # host-f64: accumulator precision
        self._pushes = 0

    # -- exact anchors ------------------------------------------------------
    @staticmethod
    def _diag_sums(M: np.ndarray, n: int) -> np.ndarray:
        """out[lag] = sum_i M[i, i + lag] for lag = 0..n-1."""
        out = np.empty(n, dtype=np.float64)  # host-f64: accumulator precision
        for lag in range(n):
            out[lag] = M.trace(offset=lag)
        return out

    def compute(self, win: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """From-scratch raw pair sums — the resync anchor and the
        parity oracle."""
        w = np.asarray(win, dtype=np.float64)  # host-f64: accumulator precision
        rt = self._diag_sums(w.T @ w, self.window)
        rf = self._diag_sums(w @ w.T, self.nf)
        return rt, rf

    def resync(self, win: np.ndarray) -> None:
        self.rt, self.rf = self.compute(win)

    # -- the incremental update ---------------------------------------------
    def push(self, before: np.ndarray, after: np.ndarray,
             c: int) -> None:
        """Advance over one ring push: ``before``/``after`` are the
        host windows around it, ``c`` the slide width (mirror of
        ``IncrementalACF.push``)."""
        c = min(int(c), self.window)
        self._pushes += 1
        if self._pushes % self.resync_every == 0 or c >= self.window:
            self.resync(after)
            return
        W = self.window
        bf = np.asarray(before, dtype=np.float64)  # host-f64: accumulator precision
        af = np.asarray(after, dtype=np.float64)  # host-f64: accumulator precision
        # time lags: a pair (i, i+lag) is lost iff its EARLIER member
        # sits in the evicted leading c columns (the later member then
        # cannot be older), gained iff its LATER member sits in the new
        # trailing c columns
        G = bf[:, :c].T @ bf                      # [c, W]
        lost = np.zeros(W, dtype=np.float64)  # host-f64: accumulator precision
        for i in range(c):
            lost[:W - i] += G[i, i:]
        H = af.T @ af[:, W - c:]                  # [W, c]
        gained = np.zeros(W, dtype=np.float64)  # host-f64: accumulator precision
        for q in range(c):
            j = W - c + q
            gained[:j + 1] += H[j::-1, q]
        self.rt = self.rt - lost + gained
        # freq lags are column-separable: each column contributes its
        # own nf x nf Gram diagonals, so evicted/added columns
        # subtract/add independently
        E = bf[:, :c]
        N = af[:, W - c:]
        self.rf = (self.rf - self._diag_sums(E @ E.T, self.nf)
                   + self._diag_sums(N @ N.T, self.nf))

    # -- the fitter-facing read ---------------------------------------------
    def cuts(self, win: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """The mean-subtracted (cut_t [W], cut_f [nf]) pair, exactly as
        ``acf_cuts_direct`` defines them, from the raw accumulators
        plus the window's column/row sums (O(nf * W))."""
        w = np.asarray(win, dtype=np.float64)  # host-f64: accumulator precision
        W, nf = self.window, self.nf
        s = w.sum(axis=0)                        # [W] column sums
        r = w.sum(axis=1)                        # [nf] row sums
        tot = float(s.sum())
        m = tot / (nf * W)
        cs = np.concatenate([[0.0], np.cumsum(s)])
        lag_t = np.arange(W)
        # earlier members: cols 0..W-1-lag; later members: cols lag..W-1
        cut_t = (self.rt - m * (cs[W - lag_t] + (tot - cs[lag_t]))
                 + nf * (W - lag_t) * m * m)
        cr = np.concatenate([[0.0], np.cumsum(r)])
        rtot = float(r.sum())
        lag_f = np.arange(nf)
        cut_f = (self.rf - m * (cr[nf - lag_f] + (rtot - cr[lag_f]))
                 + (nf - lag_f) * W * m * m)
        return cut_t, cut_f


def sliding_unsupported(config) -> str | None:
    """Why ``config`` cannot run the incremental sspec update (None if
    it can).  The decomposition needs the default prewhitened chain:
    the 2x2 second difference is what makes the interior columns
    shift-invariant, and the fused-kernel route is a different (not
    byte-matching) epilogue."""
    if not config.prewhite:
        return ("incremental ticks need prewhite=True (the second "
                "difference is what cancels the window means in the "
                "sliding state)")
    if config.fused_sspec:
        return ("incremental ticks update the default sspec chain; "
                "disable fused_sspec for streaming sessions")
    if not config.split_programs:
        return "incremental ticks ride the split-programs back-end"
    return None


class SlidingSspec:
    """Device-resident sliding-window secondary-spectrum front.

    Built from a split pipeline step's geometry
    (``_SplitStep.inc_geom``) for one ``(window, hop)``; holds the
    transform state ``S [Rs, ncfft]`` (the freq-rfft x time-fft of the
    shift-invariant prewhitened interior, delay rows cropped to the
    consumed ``Rs``) plus the resampled lead-column buffer the next
    departure update needs.  Two jitted programs:

    * ``rebuild(win)``: from-scratch state (first tick / resync).
    * ``advance(S, lead, win, cut_t, cut_f)``: one hop-slide — the
      sliding-DFT recurrence ``S' = twiddle * (S - D) + A`` with
      departing/arriving contributions as ``[Rs, nf_s-1] x
      [nf_s-1, hop] x [hop, ncfft]`` GEMMs, plus the fresh edge-taper
      correction, then the exact epilogue of ``ops.sspec._sspec_jax``
      (|.|^2, Doppler fftshift, positive-delay crop, postdark, dB) and
      the same parts dict the split front hands the fitter back-end.

    The bases (cropped rfft matrix ``Fr``, the column DFT bases, the
    taper slopes) are host-precomputed in f64 and embedded as
    canonical-dtype trace constants.
    """

    def __init__(self, step, window: int, hop: int):
        geom = getattr(step, "inc_geom", None)
        if geom is None:
            raise ValueError("SlidingSspec needs a split pipeline step "
                             "(PipelineConfig.split_programs)")
        cfg = geom["config"]
        reason = sliding_unsupported(cfg)
        if reason:
            raise ValueError(reason)
        W = int(window)
        hop = int(hop)
        if not 1 <= hop < W:
            raise ValueError(f"SlidingSspec: need 1 <= hop < window, "
                             f"got hop={hop}, window={W}")
        self.window, self.hop = W, hop
        self.cfg = cfg
        self.nf = int(geom["nf"])
        self.nf_s = int(geom["nf_s"])
        self._W_np = geom["W_np"]          # [nf_s, nf] or None
        self.dt, self.df = geom["dt"], geom["df"]
        self.dims = dict(geom["dims"])
        self._build_arc_fitter = geom["build_arc_fitter"]
        from ..ops.sspec import fft_lens
        from ..ops.windows import split_window

        nrfft, ncfft = fft_lens(self.nf_s, W, cfg.fft_lens)
        self.nrfft, self.ncfft = nrfft, ncfft
        crop = geom["crop_rows"]
        self.Rs = int(crop) if crop is not None else nrfft // 2 + 1
        self.final_rows = min(self.Rs, nrfft // 2)
        # split edge tapers on the RESAMPLED grid (the order the front
        # applies them: resample first, window inside sspec)
        if cfg.window is not None:
            tw = split_window(W, cfg.window, cfg.window_frac)
            fw = split_window(self.nf_s, cfg.window, cfg.window_frac)
            m = int(np.floor(cfg.window_frac * W))
            cut = int(np.ceil(m / 2))
        else:
            tw, fw = np.ones(W), np.ones(self.nf_s)
            m = cut = 0
        self._tw, self._fw = tw, fw
        # edge-column support of p_edge + the mean term: head columns
        # j in [0, cut), tail columns j in [W-(m-cut)-1, W-1)
        self._head = cut
        self._jt0 = W - (m - cut) - 1 if m > cut else None
        self._bases = self._build_bases()
        self._advance_fn = None
        self._rebuild_fn = None
        # live state (device arrays once built)
        self.S = None
        self.lead = None

    # -- host-precomputed constants -----------------------------------------
    def _build_bases(self) -> dict:
        W, hop, ncfft, nrfft = (self.window, self.hop, self.ncfft,
                                self.nrfft)
        k = np.arange(ncfft)
        tau = -2j * np.pi / ncfft
        b: dict = {}
        # cropped freq-axis rfft as a direct GEMM (Rs rows only — the
        # whole point of sspec_crop is that nothing past them is read)
        r = np.arange(self.Rs)
        f = np.arange(self.nf_s - 1)
        b["Fr"] = np.exp(-2j * np.pi * np.outer(r, f) / nrfft)
        b["twid"] = np.exp(-tau * hop * k)          # shift phase w^-hop*k
        b["Bd"] = np.exp(tau * np.outer(np.arange(hop), k))
        b["Ba"] = np.exp(tau * np.outer(W - 1 - hop + np.arange(hop), k))
        tw, fw = self._tw, self._fw
        b["dfw"] = fw[1:] - fw[:-1]                  # [nf_s - 1]
        if self._head:
            h = self._head
            b["Beh"] = np.exp(tau * np.outer(np.arange(h), k))
            b["eh0"], b["eh1"] = tw[:h] - 1.0, tw[1:h + 1] - 1.0
            b["dtw_h"] = tw[1:h + 1] - tw[:h]
        if self._jt0 is not None:
            j0 = self._jt0
            n_t = (W - 1) - j0
            b["Bet"] = np.exp(tau * np.outer(j0 + np.arange(n_t), k))
            b["et0"] = tw[j0:W - 1] - 1.0
            b["et1"] = tw[j0 + 1:W] - 1.0
            b["dtw_t"] = tw[j0 + 1:W] - tw[j0:W - 1]
        return b

    # -- traced helpers ------------------------------------------------------
    def _programs(self):
        """Build (once) the jitted rebuild/advance programs."""
        if self._advance_fn is not None:
            return self._rebuild_fn, self._advance_fn
        import jax
        import jax.numpy as jnp

        from ..fit.scint_fit import scint_cat_front
        from ..ops.sspec import _postdark

        cfg = self.cfg
        W, hop, nf_s = self.window, self.hop, self.nf_s
        nrfft, ncfft = self.nrfft, self.ncfft
        Rs, final_rows = self.Rs, self.final_rows
        fdt = jnp.result_type(float)
        cdt = jnp.result_type(1j * np.float32(1))
        bases = {k: jnp.asarray(v, dtype=cdt if np.iscomplexobj(v)
                                else fdt)
                 for k, v in self._bases.items()}
        fw = jnp.asarray(self._fw, dtype=fdt)
        Wm = (None if self._W_np is None
              else jnp.asarray(self._W_np, dtype=fdt))
        svec = (self._W_np.sum(axis=0) if self._W_np is not None
                else np.ones(self.nf))
        svec = jnp.asarray(svec, dtype=fdt)
        head, jt0 = self._head, self._jt0
        rung = self.dims.get("scint_rung")
        dt, df = self.dt, self.df
        fit_scint, fit_arc = cfg.fit_scint, cfg.fit_arc
        build_fitter = self._build_arc_fitter
        nf_raw = self.nf
        pd = _postdark(nrfft, ncfft, xp=np)[:final_rows]  # host-f64: grid constant, cast below

        def resample(cols):
            if Wm is None:
                return cols
            return jnp.einsum("lf,fk->lk", Wm, cols)

        def vx_of(cols):
            """Frequency difference of the row-tapered columns."""
            ub = cols * fw[:, None]
            return ub[1:] - ub[:-1]

        def pflat_of(cols):
            v = vx_of(cols)
            return v[:, 1:] - v[:, :-1]

        def edge_correction(win_f, m_g):
            """(Fr @ p_edge) @ Be over the taper-support columns; the
            mean term -m*dfw x dtw shares the support and folds in."""
            F = jnp.zeros((Rs, ncfft), dtype=cdt)
            if head:
                xh = resample(win_f[:, :head + 1])
                vh = vx_of(xh)                       # [nf_s-1, head+1]
                pe = (bases["eh1"][None, :] * vh[:, 1:]
                      - bases["eh0"][None, :] * vh[:, :-1]
                      - m_g * (bases["dfw"][:, None]
                               * bases["dtw_h"][None, :]))
                F = F + (bases["Fr"] @ pe.astype(cdt)) @ bases["Beh"]
            if jt0 is not None:
                xt = resample(win_f[:, jt0:])
                vt = vx_of(xt)
                pe = (bases["et1"][None, :] * vt[:, 1:]
                      - bases["et0"][None, :] * vt[:, :-1]
                      - m_g * (bases["dfw"][:, None]
                               * bases["dtw_t"][None, :]))
                F = F + (bases["Fr"] @ pe.astype(cdt)) @ bases["Bet"]
            return F

        def epilogue(T):
            """Mirror of ops.sspec._sspec_jax past the FFT: |.|^2,
            Doppler fftshift, positive-delay rows, postdark, dB."""
            sec = jnp.real(T) ** 2 + jnp.imag(T) ** 2
            sec = jnp.fft.fftshift(sec, axes=-1)[:final_rows]
            sec = sec / jnp.asarray(pd, dtype=sec.dtype)
            return 10.0 * jnp.log10(sec)

        def rebuild(win):
            win_f = win.astype(fdt)
            x = resample(win_f)
            P = jnp.fft.fft(pflat_of(x).astype(cdt), n=ncfft, axis=-1)
            S = bases["Fr"] @ P
            return S, x[:, :hop + 1]

        def advance(S, lead, win, cut_t, cut_f):
            win_f = win.astype(fdt)
            # departing columns (old window's lead buffer) and arriving
            # columns (new window's tail) as rank-hop GEMM updates
            D = (bases["Fr"] @ pflat_of(lead).astype(cdt)) @ bases["Bd"]
            tail = resample(win_f[:, W - hop - 1:])
            A = (bases["Fr"] @ pflat_of(tail).astype(cdt)) @ bases["Ba"]
            S2 = bases["twid"][None, :] * (S - D) + A
            m_g = jnp.einsum("f,ft->", svec, win_f) / (nf_s * W)
            T = S2 + edge_correction(win_f, m_g)
            parts = {}
            if fit_scint:
                parts.update(scint_cat_front(
                    cut_t.astype(fdt)[None], cut_f.astype(fdt)[None],
                    dt, df, rung))
            if fit_arc:
                sec_b = epilogue(T)[None]
                fitter = build_fitter((1, nf_raw, W),
                                      win.dtype.itemsize)
                prof, noise = jax.vmap(fitter.profile_of)(sec_b)
                parts["prof"] = prof
                parts["noise"] = noise
            lead2 = resample(win_f[:, :hop + 1])
            return S2, lead2, parts

        self._rebuild_fn = jax.jit(rebuild)
        self._advance_fn = jax.jit(advance)
        return self._rebuild_fn, self._advance_fn

    # -- session-facing API --------------------------------------------------
    @property
    def ready(self) -> bool:
        return self.S is not None

    def reset(self) -> None:
        """Drop the device state (restore / divergence): the next tick
        must be a full-path rebuild."""
        self.S = None
        self.lead = None

    def rebuild(self, win_dev) -> None:
        """From-scratch state over the current device window (runs at
        every full-path/resync tick — the drift re-anchor)."""
        rebuild_fn, _ = self._programs()
        self.S, self.lead = rebuild_fn(win_dev)

    def advance(self, win_dev, cut_t=None, cut_f=None) -> dict:
        """One hop-slide: update the state and return the split
        back-end ``parts`` dict for the new window."""
        if self.S is None:
            raise RuntimeError("SlidingSspec.advance before rebuild")
        _, advance_fn = self._programs()
        if cut_t is None:
            cut_t = np.zeros(self.window, dtype=np.float32)
        if cut_f is None:
            cut_f = np.zeros(self.nf, dtype=np.float32)
        self.S, self.lead, parts = advance_fn(
            self.S, self.lead, win_dev,
            np.asarray(cut_t, dtype=np.float32),
            np.asarray(cut_f, dtype=np.float32))
        return parts
