"""Streaming ingest plane (ISSUE 15): live dynspec feeds.

Everything else in the pipeline is batch — a dynamic spectrum must be
complete on disk before ``submit``.  Real observatories emit the time
axis incrementally; this package is the append-mode counterpart:

* :mod:`~scintools_tpu.stream.ingest` — a durable chunk-append log
  (atomic chunk files + a manifest, torn-tail tolerant like the
  results plane's segments) grown column-by-column along the time
  axis, plus a device-resident :class:`~scintools_tpu.stream.ingest.
  Ring` over the last W samples and an incrementally-maintained
  time-lag ACF cut over that ring;
* :mod:`~scintools_tpu.stream.window` — sliding-window recompute
  ticks whose ``(1, nf, W)`` window shape is ONE fixed bucket-catalog
  signature, so a warmed session never recompiles per tick;
* :mod:`~scintools_tpu.stream.incremental` — the ISSUE 17 O(hop)
  hot path: :class:`~scintools_tpu.stream.incremental.SlidingSspec`
  (rank-``hop`` sliding update of the time-axis DFT front) and
  :class:`~scintools_tpu.stream.incremental.IncrementalCuts`
  (host-f64 pair-sum fitter cuts), re-anchored by periodic exact
  resync (``resync_every``) — docs/streaming.md "Incremental ticks".

The serve layer registers feeds as a ``stream`` job kind
(``JobQueue.submit_stream`` / ``scintools-tpu submit QDIR --stream
FEED``): the worker polls registered feeds between batch claims and
publishes eta/tau/dnu per tick as VERSIONED result rows
(``ResultsStore.put_versioned``) — live curvature/timescale tracking
across an observation.  docs/streaming.md documents the log format,
the window/tick semantics and the versioned-row contract.
"""

from .incremental import (DEFAULT_RESYNC_EVERY, IncrementalCuts,
                          SlidingSspec)
from .ingest import (FeedError, FeedReader, FeedWriter, IncrementalACF,
                     Ring, chunk_rung, preflight_chunk)
from .window import (DEFAULT_HOP, DEFAULT_WINDOW, StreamSession,
                     validate_stream_spec)

__all__ = [
    "FeedError", "FeedReader", "FeedWriter", "IncrementalACF", "Ring",
    "chunk_rung", "preflight_chunk",
    "DEFAULT_HOP", "DEFAULT_WINDOW", "StreamSession",
    "validate_stream_spec",
    "DEFAULT_RESYNC_EVERY", "IncrementalCuts", "SlidingSspec",
]
