"""Streaming ingest plane (ISSUE 15): live dynspec feeds.

Everything else in the pipeline is batch — a dynamic spectrum must be
complete on disk before ``submit``.  Real observatories emit the time
axis incrementally; this package is the append-mode counterpart:

* :mod:`~scintools_tpu.stream.ingest` — a durable chunk-append log
  (atomic chunk files + a manifest, torn-tail tolerant like the
  results plane's segments) grown column-by-column along the time
  axis, plus a device-resident :class:`~scintools_tpu.stream.ingest.
  Ring` over the last W samples and an incrementally-maintained
  time-lag ACF cut over that ring;
* :mod:`~scintools_tpu.stream.window` — sliding-window recompute
  ticks whose ``(1, nf, W)`` window shape is ONE fixed bucket-catalog
  signature, so a warmed session never recompiles per tick.

The serve layer registers feeds as a ``stream`` job kind
(``JobQueue.submit_stream`` / ``scintools-tpu submit QDIR --stream
FEED``): the worker polls registered feeds between batch claims and
publishes eta/tau/dnu per tick as VERSIONED result rows
(``ResultsStore.put_versioned``) — live curvature/timescale tracking
across an observation.  docs/streaming.md documents the log format,
the window/tick semantics and the versioned-row contract.
"""

from .ingest import (FeedError, FeedReader, FeedWriter, IncrementalACF,
                     Ring, chunk_rung, preflight_chunk)
from .window import (DEFAULT_HOP, DEFAULT_WINDOW, StreamSession,
                     validate_stream_spec)

__all__ = [
    "FeedError", "FeedReader", "FeedWriter", "IncrementalACF", "Ring",
    "chunk_rung", "preflight_chunk",
    "DEFAULT_HOP", "DEFAULT_WINDOW", "StreamSession",
    "validate_stream_spec",
]
