"""Sliding-window recompute: fixed-signature ticks over a live feed.

A :class:`StreamSession` follows one feed and re-issues the survey fit
over the last W time samples each time ``hop`` new samples have
arrived ("a tick").  The whole design exists to make a tick CHEAP on a
warm process:

* the window shape is pinned to ``(1, nf, W)`` with a 0-based relative
  time axis, so every tick of the whole observation executes ONE
  compiled signature — a member of the PR 6 bucket catalog (batch rung
  1), servable from a warm-cache artifact, with ``jit_cache_miss == 0``
  across ticks once warmed (tier-1 counter-asserted);
* the window lives in HBM (:class:`~scintools_tpu.stream.ingest.Ring`)
  — per-tick H2D is the rung-padded chunk, not the window;
* the time-lag ACF cut is maintained incrementally over the ring
  (:class:`~scintools_tpu.stream.ingest.IncrementalACF`) rather than
  from scratch — the live timescale proxy between fits;
* each appended chunk passes the preflight data-quality gate
  (:func:`~scintools_tpu.stream.ingest.preflight_chunk`): a bad chunk
  is MASKED (chunk-local deterministic repair) and counted
  (``chunks_quarantined[<reason>]``), never fatal to the stream.

The canonical eta/tau/dnu per tick come from the SAME pipeline the
batch path runs (the identical memoised ``make_pipeline`` step), so a
final full window's fit row is byte-identical to a one-shot batch run
over the same data — the acceptance contract.

Crash recovery: a session serialises to a tiny cursor dict
(:meth:`StreamSession.state`); :meth:`restore` replays the last W
samples from the feed log (masking is chunk-local, hence
deterministic) and resumes ticking where the dead session stopped —
no duplicate and no lost versioned rows, because tick rows are keyed
by window-end sample.
"""

from __future__ import annotations

import os
import time

import numpy as np

from .. import faults, obs
from ..obs.hist import Hist
from ..utils.log import get_logger, log_event
from .incremental import (DEFAULT_RESYNC_EVERY, IncrementalCuts,
                          SlidingSspec, sliding_unsupported)
from .ingest import (FeedError, FeedReader, IncrementalACF, Ring,
                     mask_chunk, preflight_chunk)

DEFAULT_WINDOW = 256
DEFAULT_HOP = 64
MIN_WINDOW = 8


def validate_stream_spec(spec: dict) -> dict:
    """Normalise/validate a ``stream`` job payload ``{feed, window,
    hop}`` plus the optional incremental-tick knobs — ONE rule site
    shared by ``JobQueue.submit_stream`` (the client-side fail-fast)
    and the worker's registration path.  ``incremental`` /
    ``resync_every`` are included in the normalised payload ONLY when
    explicitly set, so pre-existing submitted jobs keep their content
    identity."""
    spec = dict(spec or {})
    feed = spec.get("feed")
    if not feed:
        raise ValueError("stream spec needs feed=<feed directory>")
    w = int(spec.get("window", DEFAULT_WINDOW))
    h = int(spec.get("hop", max(w // 4, 1)))
    if w < MIN_WINDOW:
        raise ValueError(f"stream window={w}: need >= {MIN_WINDOW} "
                         "time samples for the fits")
    if not 1 <= h <= w:
        raise ValueError(f"stream hop={h}: need 1 <= hop <= window "
                         f"({w})")
    out = {"feed": os.path.abspath(str(feed)), "window": w, "hop": h}
    if "incremental" in spec:
        inc = bool(spec["incremental"])
        if inc and h >= w:
            raise ValueError(f"stream incremental=True needs hop < "
                             f"window, got hop={h}, window={w}")
        out["incremental"] = inc
    if "resync_every" in spec:
        r = int(spec["resync_every"])
        if r < 1:
            raise ValueError(f"stream resync_every={r}: need >= 1")
        out["resync_every"] = r
    return out


def read_feed_window(reader: FeedReader, end: int, window: int,
                     dtype) -> np.ndarray:
    """The masked ``[nf, window]`` block ending at committed sample
    ``end``, replayed from the feed log.  Masking is chunk-local
    (:func:`~.ingest.mask_chunk`), so this replays to the SAME bytes
    the live ring held over that span — the property both crash
    recovery (:meth:`StreamSession.restore`) and the backfill lane's
    catch-up windows rely on."""
    out = np.zeros((reader.nf, window), dtype=dtype)
    start_want = end - window
    for start, rec in reader.chunks_since(0):
        cend = start + int(rec["nt"])
        if cend <= max(start_want, 0) or start >= end:
            continue
        arr = np.asarray(reader.read_chunk(rec))
        if preflight_chunk(arr):
            arr = mask_chunk(arr)
        arr = arr.astype(dtype)
        lo = max(start_want - start, 0)
        hi = int(rec["nt"]) - max(cend - end, 0)
        piece = arr[:, lo:hi]
        pos = window - (end - (start + lo))
        out[:, pos:pos + piece.shape[1]] = piece
    return out


def backfill_tick_ends(reader: FeedReader, window: int, hop: int,
                       upto: int) -> list[tuple[int, int]]:
    """``(window_end, tick_index)`` for every live-cadence tick whose
    end sample is ``<= upto`` — the manifest replayed under the SAME
    cadence rule the live session uses (full ring, then every chunk
    boundary >= hop samples since the last tick), so the backfill
    lane's rows land on exactly the window-end keys AND the 1-based
    tick numbers the skipped live ticks would have published."""
    ends: list[tuple[int, int]] = []
    consumed = 0
    last = None
    tick = 1
    for rec in reader.manifest["chunks"]:
        consumed += int(rec["nt"])
        if consumed < window:
            continue
        if last is None or consumed - last >= hop:
            if consumed > upto:
                break
            ends.append((consumed, tick))
            last = consumed
            tick += 1
    return ends


def stream_row_base(reader: FeedReader, window: int, dt: float,
                    window_end: int, tick: int, final: bool) -> dict:
    """The identity/axis columns of one stream result row — shared by
    the live session's ticks and the backfill lane's catch-up rows so
    both publish the same schema for the same window-end key."""
    man = reader.manifest
    freqs = reader.freqs()
    df = float(freqs[1] - freqs[0]) if len(freqs) > 1 else 1.0
    return {
        "name": f"{reader.name}@{'final' if final else 'w%d' % window_end}",
        "mjd": float(man.get("mjd", 50000.0)),
        "freq": round(float(np.mean(freqs)), 2),
        "bw": float(abs(freqs[-1] - freqs[0])) + abs(df),
        "tobs": window * dt, "dt": dt, "df": df,
        "window_end": int(window_end), "tick": int(tick),
        "window": int(window), "final": bool(final),
    }


class StreamSession:
    """Incremental-recompute consumer of one feed.

    ``opts`` is the serve option dict (the same estimator flags a
    batch job carries); a mesh is not supported — a streaming window
    is a single-device residency by design (shard across FEEDS, not
    within one window)."""

    def __init__(self, feed_dir: str, opts: dict | None = None,
                 window: int = DEFAULT_WINDOW, hop: int = DEFAULT_HOP,
                 nlags: int | None = None, incremental: bool = False,
                 resync_every: int | None = None):
        import dataclasses

        from ..parallel.driver import stage_dtype
        from ..serve.worker import config_from_opts

        spec = validate_stream_spec({"feed": feed_dir, "window": window,
                                     "hop": hop,
                                     "incremental": incremental})
        self.window = spec["window"]
        self.hop = spec["hop"]
        self.opts = dict(opts or {})
        self.opts.pop("stream", None)   # the payload is not an estimator knob
        self.cfg = config_from_opts(self.opts)
        if self.cfg.arc_stack:
            raise ValueError("arc_stack is a campaign knob; a stream "
                             "tick fits one window")
        self.incremental = bool(incremental)
        self.resync_every = int(resync_every or DEFAULT_RESYNC_EVERY)
        if self.incremental:
            # incremental ticks ride the split-programs back-end (the
            # warm-start seed is a runtime input of its shared fitter
            # unit); forcing the split here is shape-safe — streaming
            # is single-device, fixed-signature by construction
            if not self.cfg.split_programs:
                self.cfg = dataclasses.replace(self.cfg,
                                               split_programs=True)
            reason = sliding_unsupported(self.cfg)
            if reason:
                raise ValueError(f"stream incremental=True: {reason}")
        self.cfg.validate()
        self.reader = FeedReader(spec["feed"])
        self.freqs = self.reader.freqs()
        self.nf = len(self.freqs)
        self.dt = self.reader.dt
        self.win_times = self.reader.times(self.window)
        self._stage_dtype = np.dtype(stage_dtype(self.cfg.precision))
        self.ring = Ring(self.nf, self.window, dtype=self._stage_dtype)
        self.acf = IncrementalACF(self.window, nlags=nlags)
        self.consumed = 0           # committed samples consumed
        self.tick_seq = 0
        self.last_tick_at = None    # consumed-sample count of last tick
        self.quarantined: dict[str, int] = {}
        self.final_done = False
        # per-session tick-latency histogram on the CLOSED bucket
        # ladder (ISSUE 16): the same counts obs.observe feeds the
        # heartbeat registry, so stats() quantiles and fleet-merged
        # quantiles read one representation — no truncated
        # process-local sample list to diverge from
        self.tick_hist = Hist()
        self._last_chunk_t = None   # producer wall stamp of newest
        self._stepfn = None         # consumed chunk (lag readout)
        # incremental-tick state (inert unless self.incremental)
        self._step_obj = None       # the _SplitStep behind _stepfn
        self._sliding = None        # SlidingSspec device state
        self.cuts = (IncrementalCuts(self.window, self.nf)
                     if self.incremental and self.cfg.fit_scint
                     else None)
        self._ticks_since_resync = 0
        self._prev_params = None    # last tick's [tau, dnu, amp, wn]
        self._quar_since_tick = False
        self.inc_ticks = 0
        self.resyncs = 0
        # backfill fast-forward (ISSUE 17): live-cadence ticks whose
        # window end is <= this cursor are SKIPPED (bookkeeping only,
        # no device work) — a submitted backfill job publishes their
        # rows through the batch path instead
        self._skip_upto = 0
        self.skipped_ticks = 0
        self.log = get_logger()

    # -- identity / durability ---------------------------------------------
    @property
    def name(self) -> str:
        return self.reader.name

    def signature(self) -> str:
        """The one compiled window signature every tick executes
        (obs-label spelling, like the bucket catalog's)."""
        return f"1x{self.nf}x{self.window}:{self._stage_dtype}"

    def state(self) -> dict:
        """The durable resume cursor (persist AFTER the tick rows'
        flush, so a crash between them replays — versioned rows make
        the replayed publishes idempotent)."""
        return {"consumed": int(self.consumed),
                "tick_seq": int(self.tick_seq),
                "last_tick_at": self.last_tick_at,
                "quarantined": dict(self.quarantined),
                "final_done": bool(self.final_done),
                "skip_upto": int(self._skip_upto)}

    def restore(self, state: dict) -> None:
        """Resume from a :meth:`state` cursor: replay the last W
        consumed samples out of the feed log (chunk-local masking
        replays to the same bytes) to rebuild the ring + ACF, then
        continue from the cursor."""
        consumed = int(state.get("consumed", 0))
        if consumed <= 0:
            return
        total = self.reader.total_samples
        if consumed > total:
            # a cursor AHEAD of the committed log (manifest rolled
            # back?) cannot replay; re-consume from scratch
            log_event(self.log, "stream_cursor_ahead", feed=self.name,
                      cursor=consumed, committed=total)
            return
        window = read_feed_window(self.reader, consumed, self.window,
                                  self._stage_dtype)
        filled = min(consumed, self.window)
        self.ring.reset(window, consumed)
        self.acf.acf = self.acf.compute(window)
        if self.cuts is not None:
            self.cuts.resync(window)
        if self._sliding is not None:
            # device transform state cannot be trusted across a resume
            # boundary: the next tick runs the full path and rebuilds
            self._sliding.reset()
        self._prev_params = None
        self._quar_since_tick = False
        self.consumed = consumed
        self.tick_seq = int(state.get("tick_seq", 0))
        self.last_tick_at = state.get("last_tick_at")
        self.quarantined = dict(state.get("quarantined") or {})
        self.final_done = bool(state.get("final_done", False))
        self._skip_upto = int(state.get("skip_upto", 0))
        log_event(self.log, "stream_resumed", feed=self.name,
                  consumed=consumed, replayed=filled,
                  ticks=self.tick_seq)

    # -- consuming the feed -------------------------------------------------
    def _consume(self, rec: dict) -> None:
        arr = np.asarray(self.reader.read_chunk(rec))
        reasons = preflight_chunk(arr)
        if reasons:
            for r in reasons:
                self.quarantined[r] = self.quarantined.get(r, 0) + 1
                obs.inc(f"chunks_quarantined[{r}]")
            obs.inc("chunks_quarantined")
            log_event(self.log, "stream_chunk_quarantined",
                      feed=self.name, seq=rec.get("seq"),
                      reasons=",".join(reasons))
            arr = mask_chunk(arr)
            self._quar_since_tick = True
        chunk = arr.astype(self._stage_dtype)
        before = self.ring.window_host()
        self.ring.push(chunk)
        self.acf.push(before, self.ring.window_host(), chunk.shape[1])
        if self.cuts is not None:
            # masked chunks flow through here like any other bytes:
            # the incremental cut state tracks the ring's host mirror,
            # which already holds the masked window
            self.cuts.push(before, self.ring.window_host(),
                           chunk.shape[1])
        self.consumed += int(rec["nt"])
        self._last_chunk_t = rec.get("t")

    def _tick_due(self) -> bool:
        if not self.ring.full:
            return False
        if self.last_tick_at is None:
            return True
        return self.consumed - self.last_tick_at >= self.hop

    def poll(self, now: float | None = None) -> list[dict]:
        """Consume newly committed chunks and run every due tick.
        Returns the tick rows (possibly empty).  A corrupt committed
        chunk raises :class:`FeedError` (deterministic poison — the
        serve worker routes it to ``failed/``)."""
        self.reader.refresh()
        rows: list[dict] = []
        try:
            # fault site: an armed fault blocks consumption (the
            # consumer genuinely falls behind the feed head), while
            # the finally still samples the growing lag — the SLO
            # smoke gate's injected freshness breach
            faults.check("stream.poll")
            for _start, rec in self.reader.chunks_since(self.consumed):
                self._consume(rec)
                if self._tick_due():
                    if self.consumed <= self._skip_upto:
                        self._skip_tick()
                    else:
                        rows.append(self._tick(now=now))
            if self.reader.finalized and not self.final_done \
                    and self.consumed >= self.reader.total_samples:
                final = self._final_tick(now=now)
                if final is not None:
                    rows.append(final)
                self.final_done = True
        finally:
            self._publish_lag(time.time() if now is None else now)
        return rows

    def skip_ticks_until(self, window_end: int) -> None:
        """Fast-forward the live cadence past a backlog (ISSUE 17):
        ticks whose window end is ``<= window_end`` advance the tick
        bookkeeping but run NO device work and publish NO row — the
        caller has submitted a backfill job that replays those windows
        through the batch path (same window-end keys, versioned rows).
        The final tick is never skipped (it runs live regardless, so
        the byte-identity acceptance gate keeps its anchor).  Durable:
        the cursor carries the mark, so a crash mid-catch-up resumes
        skipping instead of replaying the backlog live."""
        self._skip_upto = max(self._skip_upto, int(window_end))

    def _skip_tick(self) -> None:
        """Advance past one backfilled tick: same tick_seq/cursor
        bookkeeping as a real tick (the backfill rows carry the tick
        indices a live replay would have), zero compute."""
        self.tick_seq += 1
        self.last_tick_at = self.consumed
        self.skipped_ticks += 1
        self._quar_since_tick = False

    @property
    def complete(self) -> bool:
        """All committed samples consumed, feed finalized, final
        window published — the stream job's completion condition."""
        return (self.final_done and self.reader.finalized
                and self.consumed >= self.reader.total_samples)

    # -- the warm fixed-signature fit ---------------------------------------
    def _ensure_step(self):
        """Build (once) the window step: the SAME memoised
        ``make_pipeline`` program the batch driver runs for a
        ``[1, nf, W]`` epoch — preferring a warm-cache AOT artifact
        exactly as ``run_pipeline`` would, so a warmed pod's first
        tick re-traces nothing."""
        if self._stepfn is not None:
            return self._stepfn
        from .. import compile_cache
        from ..parallel.driver import _SplitStep, make_pipeline

        step = make_pipeline(self.freqs, self.win_times, self.cfg,
                             mesh=None, donate=False)
        if isinstance(step, _SplitStep):
            self._step_obj = step
            if self.incremental:
                self._sliding = SlidingSspec(step, self.window,
                                             self.hop)
            self._stepfn = step.instrumented()
            return self._stepfn
        aot = None
        if compile_cache.cache_dir() is not None:
            compile_cache.enable_persistent_cache()
            aot = compile_cache.load_step(compile_cache.step_key(
                self.freqs, self.win_times, self.cfg, None, False,
                (1, self.nf, self.window), self._stage_dtype,
                donate=False))
        self._stepfn = (obs.instrument_jit(aot, "pipeline.step",
                                           aot=True)
                        if aot is not None
                        else obs.instrument_jit(step, "pipeline.step"))
        return self._stepfn

    def _row_base(self, window_end: int, final: bool) -> dict:
        return stream_row_base(self.reader, self.window, self.dt,
                               window_end, self.tick_seq, final)

    def _publish_metrics(self, latency: float, now: float) -> None:
        obs.inc("stream_ticks")
        obs.observe("tick_latency_s", latency)
        self.tick_hist.observe(latency)

    def _publish_lag(self, now: float) -> None:
        """Per-POLL lag sample: gauges for the operator timeline plus
        a per-feed bucket-ladder histogram observation (the freshness
        SLO source — sampled even when a stalled feed yields no tick,
        so a breach keeps producing evidence; ISSUE 16)."""
        lag = self.lag_s(now)
        if lag is not None:
            obs.gauge("stream_lag_s", round(lag, 6), stream=True)
            obs.gauge(f"stream_lag_s[{self.name}]", round(lag, 6))
            obs.observe(f"stream_lag_s[{self.name}]", lag)

    def lag_s(self, now: float | None = None) -> float | None:
        """Processing lag behind the feed head: wall seconds since the
        newest CONSUMED chunk was appended by the producer (the
        ``stream_lag_s`` gauge; None before any consumption)."""
        if self._last_chunk_t is None:
            return None
        now = time.time() if now is None else now
        return max(now - float(self._last_chunk_t), 0.0)

    def _measure_row(self, res, lane: int = 0) -> dict:
        from ..io.results import batch_lane_row

        return batch_lane_row(res, lane, self.cfg.lamsteps)

    # -- incremental ticks ---------------------------------------------------
    def _incremental_due(self) -> bool:
        """Whether THIS tick may take the O(hop) path: device state
        anchored, the slide since the last tick exactly ``hop`` (an
        oversize chunk or a catch-up burst changes the shift width the
        sliding-DFT recurrence was built for), and the resync cadence
        not yet due."""
        return (self.incremental
                and self._sliding is not None
                and self._sliding.ready
                and self.last_tick_at is not None
                and self.consumed - self.last_tick_at == self.hop
                and self._ticks_since_resync < self.resync_every)

    def _warm_steps(self) -> int:
        return max(1, int(self.cfg.lm_steps) // 2)

    def _after_full_tick(self) -> None:
        """Re-anchor the incremental state after a full-path tick: the
        full program just produced the exact row, so rebuilding from
        the same device window makes resync ticks byte-identical to
        the batch path BY CONSTRUCTION (same program, same bytes)."""
        self._sliding.rebuild(self.ring.window_device())
        if self.cuts is not None:
            self.cuts.resync(self.ring.window_host())
        self._ticks_since_resync = 0
        self.resyncs += 1
        obs.inc("tick_resyncs")
        obs.inc("lm_steps", int(self.cfg.lm_steps))

    def _incremental_tick(self):
        """The O(hop) tick: slide the sspec transform state, read the
        incremental cuts, seed the fitter from the previous tick, and
        run ONLY the shared back unit."""
        cut_t = cut_f = None
        if self.cuts is not None:
            cut_t, cut_f = self.cuts.cuts(self.ring.window_host())
        parts = self._sliding.advance(self.ring.window_device(),
                                      cut_t, cut_f)
        if self.cfg.fit_scint:
            # a usable seed is a HEALTHY previous fit: finite, interior
            # (tau/dnu pinned at the 1e-10 LM lower bound mark a
            # diverged tick — re-seeding there strands the fit), and
            # not computed over a window a masked chunk has since
            # rewritten
            warm = (self._prev_params is not None
                    and not self._quar_since_tick
                    and bool(np.all(np.isfinite(self._prev_params)))
                    and bool(np.all(self._prev_params[:2] > 1e-9))
                    and bool(np.all(self._prev_params[2:] >= 0)))
            if warm:
                steps_rt = self._warm_steps()
                p0 = np.asarray(
                    self._prev_params,
                    dtype=np.dtype(parts["scint_p0"].dtype))[None]
                parts["scint_p0"] = p0
                obs.inc("warm_start_seeded")
            else:
                # diverged / quarantine-masked tick: cold default seed
                # at the full iteration budget — but STILL through the
                # dynamic trace (a stable warm signature beats saving
                # the key), so steps_rt carries the full count
                steps_rt = int(self.cfg.lm_steps)
                obs.inc("warm_start_fallbacks")
            parts["lm_steps_rt"] = np.int32(steps_rt)
            obs.inc("lm_steps", steps_rt)
        res = self._step_obj.bind_parts(
            parts, back_fn=self._step_obj.instrumented_back())
        self._ticks_since_resync += 1
        self.inc_ticks += 1
        obs.inc("incremental_ticks")
        return res

    def _remember_fit(self, res) -> None:
        if not (self.incremental and self.cfg.fit_scint):
            return
        s = res.scint
        if s is None or s.amp is None or s.wn is None:
            self._prev_params = None
            return
        self._prev_params = np.array(
            [float(np.asarray(s.tau)[0]), float(np.asarray(s.dnu)[0]),
             float(np.asarray(s.amp)[0]), float(np.asarray(s.wn)[0])])

    def _tick(self, now: float | None = None) -> dict:
        """One sliding-window recompute over the HBM-resident ring:
        the fixed-signature compiled fit + the incremental-ACF
        timescale proxy, emitted as one result row.  In incremental
        mode the between-resync ticks take the O(hop) sliding-update
        path instead of the full program."""
        t0 = time.perf_counter()
        step = self._ensure_step()
        inc = self._incremental_due()
        with obs.span("stream.tick", feed=self.name,
                      window_end=self.consumed):
            if inc:
                res = self._incremental_tick()
            else:
                res = step(self.ring.window_device()[None])
                if self.incremental and self._sliding is not None:
                    self._after_full_tick()
        self._remember_fit(res)
        self._quar_since_tick = False
        self.tick_seq += 1
        self.last_tick_at = self.consumed
        row = self._row_base(self.consumed, final=False)
        if inc:
            row["incremental"] = True
        row.update(self._measure_row(res))
        hw = self.acf.halfwidth_s(self.dt)
        if hw is not None:
            row["acf_halfwidth_s"] = round(hw, 6)
        row["quarantined_chunks"] = int(sum(self.quarantined.values()))
        latency = time.perf_counter() - t0
        row["tick_latency_s"] = round(latency, 6)
        self._publish_metrics(latency,
                              time.time() if now is None else now)
        return row

    def _final_tick(self, now: float | None = None) -> dict | None:
        """The finalized feed's last word: one more full-window tick
        over the completed tail, marked ``final`` — the row the
        byte-identity acceptance gate compares against a one-shot
        batch run of the same data (re-running the warm signature is
        cheaper than special-casing "the last hop already ticked
        here").  A feed SHORTER than the window cannot execute the
        fixed signature: it runs the one-shot batch path over the
        actual samples instead (its own — warmable — signature),
        marked ``partial_window``."""
        if self.ring.full:
            row = self._tick(now=now)
            row["final"] = True
            row["name"] = f"{self.name}@final"
            return row
        # short feed: one-shot batch fit over what arrived
        from ..parallel import run_pipeline

        t0 = time.perf_counter()
        try:
            epoch = self.reader.epoch()
        except FeedError:
            return None
        ((idx, res),) = run_pipeline([epoch], self.cfg,
                                     async_exec=False)
        del idx
        self.tick_seq += 1
        self.last_tick_at = self.consumed
        row = self._row_base(self.consumed, final=True)
        row["tobs"] = epoch.nsub * self.dt
        row["partial_window"] = True
        row.update(self._measure_row(res))
        row["quarantined_chunks"] = int(sum(self.quarantined.values()))
        latency = time.perf_counter() - t0
        row["tick_latency_s"] = round(latency, 6)
        self._publish_metrics(latency,
                              time.time() if now is None else now)
        return row

    # -- telemetry ----------------------------------------------------------
    def stats(self) -> dict:
        """The per-feed heartbeat/fleet payload."""
        return {
            "feed": self.name, "window": self.window, "hop": self.hop,
            # the PINNING key (serve/pool folds this into per-worker
            # claim hints): the feed's absolute path, matching what
            # queue.stream_feed_of reads off the job payload
            "dir": os.path.abspath(self.reader.dir),
            "ticks": int(self.tick_seq),
            "skipped": int(self.skipped_ticks),
            "incremental": bool(self.incremental),
            "inc_ticks": int(self.inc_ticks),
            "resyncs": int(self.resyncs),
            "consumed": int(self.consumed),
            "committed": int(self.reader.total_samples),
            "finalized": self.reader.finalized,
            "quarantined": int(sum(self.quarantined.values())),
            "lag_s": (round(self.lag_s(), 3)
                      if self._last_chunk_t is not None else None),
            "tick_latency_s": ({
                "p50": round(self.tick_hist.quantile(0.50), 6),
                "p95": round(self.tick_hist.quantile(0.95), 6)}
                if self.tick_hist.n else None),
        }
