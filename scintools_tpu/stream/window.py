"""Sliding-window recompute: fixed-signature ticks over a live feed.

A :class:`StreamSession` follows one feed and re-issues the survey fit
over the last W time samples each time ``hop`` new samples have
arrived ("a tick").  The whole design exists to make a tick CHEAP on a
warm process:

* the window shape is pinned to ``(1, nf, W)`` with a 0-based relative
  time axis, so every tick of the whole observation executes ONE
  compiled signature — a member of the PR 6 bucket catalog (batch rung
  1), servable from a warm-cache artifact, with ``jit_cache_miss == 0``
  across ticks once warmed (tier-1 counter-asserted);
* the window lives in HBM (:class:`~scintools_tpu.stream.ingest.Ring`)
  — per-tick H2D is the rung-padded chunk, not the window;
* the time-lag ACF cut is maintained incrementally over the ring
  (:class:`~scintools_tpu.stream.ingest.IncrementalACF`) rather than
  from scratch — the live timescale proxy between fits;
* each appended chunk passes the preflight data-quality gate
  (:func:`~scintools_tpu.stream.ingest.preflight_chunk`): a bad chunk
  is MASKED (chunk-local deterministic repair) and counted
  (``chunks_quarantined[<reason>]``), never fatal to the stream.

The canonical eta/tau/dnu per tick come from the SAME pipeline the
batch path runs (the identical memoised ``make_pipeline`` step), so a
final full window's fit row is byte-identical to a one-shot batch run
over the same data — the acceptance contract.

Crash recovery: a session serialises to a tiny cursor dict
(:meth:`StreamSession.state`); :meth:`restore` replays the last W
samples from the feed log (masking is chunk-local, hence
deterministic) and resumes ticking where the dead session stopped —
no duplicate and no lost versioned rows, because tick rows are keyed
by window-end sample.
"""

from __future__ import annotations

import time

import numpy as np

from .. import faults, obs
from ..obs.hist import Hist
from ..utils.log import get_logger, log_event
from .ingest import (FeedError, FeedReader, IncrementalACF, Ring,
                     mask_chunk, preflight_chunk)

DEFAULT_WINDOW = 256
DEFAULT_HOP = 64
MIN_WINDOW = 8


def validate_stream_spec(spec: dict) -> dict:
    """Normalise/validate a ``stream`` job payload ``{feed, window,
    hop}`` — ONE rule site shared by ``JobQueue.submit_stream`` (the
    client-side fail-fast) and the worker's registration path."""
    import os

    spec = dict(spec or {})
    feed = spec.get("feed")
    if not feed:
        raise ValueError("stream spec needs feed=<feed directory>")
    w = int(spec.get("window", DEFAULT_WINDOW))
    h = int(spec.get("hop", max(w // 4, 1)))
    if w < MIN_WINDOW:
        raise ValueError(f"stream window={w}: need >= {MIN_WINDOW} "
                         "time samples for the fits")
    if not 1 <= h <= w:
        raise ValueError(f"stream hop={h}: need 1 <= hop <= window "
                         f"({w})")
    return {"feed": os.path.abspath(str(feed)), "window": w, "hop": h}


class StreamSession:
    """Incremental-recompute consumer of one feed.

    ``opts`` is the serve option dict (the same estimator flags a
    batch job carries); a mesh is not supported — a streaming window
    is a single-device residency by design (shard across FEEDS, not
    within one window)."""

    def __init__(self, feed_dir: str, opts: dict | None = None,
                 window: int = DEFAULT_WINDOW, hop: int = DEFAULT_HOP,
                 nlags: int | None = None):
        from ..parallel.driver import stage_dtype
        from ..serve.worker import config_from_opts

        spec = validate_stream_spec({"feed": feed_dir, "window": window,
                                     "hop": hop})
        self.window = spec["window"]
        self.hop = spec["hop"]
        self.opts = dict(opts or {})
        self.opts.pop("stream", None)   # the payload is not an estimator knob
        self.cfg = config_from_opts(self.opts)
        if self.cfg.arc_stack:
            raise ValueError("arc_stack is a campaign knob; a stream "
                             "tick fits one window")
        self.cfg.validate()
        self.reader = FeedReader(spec["feed"])
        self.freqs = self.reader.freqs()
        self.nf = len(self.freqs)
        self.dt = self.reader.dt
        self.win_times = self.reader.times(self.window)
        self._stage_dtype = np.dtype(stage_dtype(self.cfg.precision))
        self.ring = Ring(self.nf, self.window, dtype=self._stage_dtype)
        self.acf = IncrementalACF(self.window, nlags=nlags)
        self.consumed = 0           # committed samples consumed
        self.tick_seq = 0
        self.last_tick_at = None    # consumed-sample count of last tick
        self.quarantined: dict[str, int] = {}
        self.final_done = False
        # per-session tick-latency histogram on the CLOSED bucket
        # ladder (ISSUE 16): the same counts obs.observe feeds the
        # heartbeat registry, so stats() quantiles and fleet-merged
        # quantiles read one representation — no truncated
        # process-local sample list to diverge from
        self.tick_hist = Hist()
        self._last_chunk_t = None   # producer wall stamp of newest
        self._stepfn = None         # consumed chunk (lag readout)
        self.log = get_logger()

    # -- identity / durability ---------------------------------------------
    @property
    def name(self) -> str:
        return self.reader.name

    def signature(self) -> str:
        """The one compiled window signature every tick executes
        (obs-label spelling, like the bucket catalog's)."""
        return f"1x{self.nf}x{self.window}:{self._stage_dtype}"

    def state(self) -> dict:
        """The durable resume cursor (persist AFTER the tick rows'
        flush, so a crash between them replays — versioned rows make
        the replayed publishes idempotent)."""
        return {"consumed": int(self.consumed),
                "tick_seq": int(self.tick_seq),
                "last_tick_at": self.last_tick_at,
                "quarantined": dict(self.quarantined),
                "final_done": bool(self.final_done)}

    def restore(self, state: dict) -> None:
        """Resume from a :meth:`state` cursor: replay the last W
        consumed samples out of the feed log (chunk-local masking
        replays to the same bytes) to rebuild the ring + ACF, then
        continue from the cursor."""
        consumed = int(state.get("consumed", 0))
        if consumed <= 0:
            return
        total = self.reader.total_samples
        if consumed > total:
            # a cursor AHEAD of the committed log (manifest rolled
            # back?) cannot replay; re-consume from scratch
            log_event(self.log, "stream_cursor_ahead", feed=self.name,
                      cursor=consumed, committed=total)
            return
        window = np.zeros((self.nf, self.window),
                          dtype=self._stage_dtype)
        filled = 0
        start_want = consumed - self.window
        for start, rec in self.reader.chunks_since(0):
            end = start + int(rec["nt"])
            if end <= max(start_want, 0) or start >= consumed:
                continue
            arr = np.asarray(self.reader.read_chunk(rec))
            if preflight_chunk(arr):
                arr = mask_chunk(arr)
            arr = arr.astype(self._stage_dtype)
            lo = max(start_want - start, 0)
            hi = int(rec["nt"]) - max(end - consumed, 0)
            piece = arr[:, lo:hi]
            pos = self.window - (consumed - (start + lo))
            window[:, pos:pos + piece.shape[1]] = piece
            filled += piece.shape[1]
        self.ring.reset(window, consumed)
        self.acf.acf = self.acf.compute(window)
        self.consumed = consumed
        self.tick_seq = int(state.get("tick_seq", 0))
        self.last_tick_at = state.get("last_tick_at")
        self.quarantined = dict(state.get("quarantined") or {})
        self.final_done = bool(state.get("final_done", False))
        log_event(self.log, "stream_resumed", feed=self.name,
                  consumed=consumed, replayed=filled,
                  ticks=self.tick_seq)

    # -- consuming the feed -------------------------------------------------
    def _consume(self, rec: dict) -> None:
        arr = np.asarray(self.reader.read_chunk(rec))
        reasons = preflight_chunk(arr)
        if reasons:
            for r in reasons:
                self.quarantined[r] = self.quarantined.get(r, 0) + 1
                obs.inc(f"chunks_quarantined[{r}]")
            obs.inc("chunks_quarantined")
            log_event(self.log, "stream_chunk_quarantined",
                      feed=self.name, seq=rec.get("seq"),
                      reasons=",".join(reasons))
            arr = mask_chunk(arr)
        chunk = arr.astype(self._stage_dtype)
        before = self.ring.window_host()
        self.ring.push(chunk)
        self.acf.push(before, self.ring.window_host(), chunk.shape[1])
        self.consumed += int(rec["nt"])
        self._last_chunk_t = rec.get("t")

    def _tick_due(self) -> bool:
        if not self.ring.full:
            return False
        if self.last_tick_at is None:
            return True
        return self.consumed - self.last_tick_at >= self.hop

    def poll(self, now: float | None = None) -> list[dict]:
        """Consume newly committed chunks and run every due tick.
        Returns the tick rows (possibly empty).  A corrupt committed
        chunk raises :class:`FeedError` (deterministic poison — the
        serve worker routes it to ``failed/``)."""
        self.reader.refresh()
        rows: list[dict] = []
        try:
            # fault site: an armed fault blocks consumption (the
            # consumer genuinely falls behind the feed head), while
            # the finally still samples the growing lag — the SLO
            # smoke gate's injected freshness breach
            faults.check("stream.poll")
            for _start, rec in self.reader.chunks_since(self.consumed):
                self._consume(rec)
                if self._tick_due():
                    rows.append(self._tick(now=now))
            if self.reader.finalized and not self.final_done \
                    and self.consumed >= self.reader.total_samples:
                final = self._final_tick(now=now)
                if final is not None:
                    rows.append(final)
                self.final_done = True
        finally:
            self._publish_lag(time.time() if now is None else now)
        return rows

    @property
    def complete(self) -> bool:
        """All committed samples consumed, feed finalized, final
        window published — the stream job's completion condition."""
        return (self.final_done and self.reader.finalized
                and self.consumed >= self.reader.total_samples)

    # -- the warm fixed-signature fit ---------------------------------------
    def _ensure_step(self):
        """Build (once) the window step: the SAME memoised
        ``make_pipeline`` program the batch driver runs for a
        ``[1, nf, W]`` epoch — preferring a warm-cache AOT artifact
        exactly as ``run_pipeline`` would, so a warmed pod's first
        tick re-traces nothing."""
        if self._stepfn is not None:
            return self._stepfn
        from .. import compile_cache
        from ..parallel.driver import _SplitStep, make_pipeline

        step = make_pipeline(self.freqs, self.win_times, self.cfg,
                             mesh=None, donate=False)
        if isinstance(step, _SplitStep):
            self._stepfn = step.instrumented()
            return self._stepfn
        aot = None
        if compile_cache.cache_dir() is not None:
            compile_cache.enable_persistent_cache()
            aot = compile_cache.load_step(compile_cache.step_key(
                self.freqs, self.win_times, self.cfg, None, False,
                (1, self.nf, self.window), self._stage_dtype,
                donate=False))
        self._stepfn = (obs.instrument_jit(aot, "pipeline.step",
                                           aot=True)
                        if aot is not None
                        else obs.instrument_jit(step, "pipeline.step"))
        return self._stepfn

    def _row_base(self, window_end: int, final: bool) -> dict:
        man = self.reader.manifest
        freqs = self.freqs
        df = float(freqs[1] - freqs[0]) if len(freqs) > 1 else 1.0
        return {
            "name": f"{self.name}@{'final' if final else 'w%d' % window_end}",
            "mjd": float(man.get("mjd", 50000.0)),
            "freq": round(float(np.mean(freqs)), 2),
            "bw": float(abs(freqs[-1] - freqs[0])) + abs(df),
            "tobs": self.window * self.dt, "dt": self.dt, "df": df,
            "window_end": int(window_end), "tick": int(self.tick_seq),
            "window": int(self.window), "final": bool(final),
        }

    def _publish_metrics(self, latency: float, now: float) -> None:
        obs.inc("stream_ticks")
        obs.observe("tick_latency_s", latency)
        self.tick_hist.observe(latency)

    def _publish_lag(self, now: float) -> None:
        """Per-POLL lag sample: gauges for the operator timeline plus
        a per-feed bucket-ladder histogram observation (the freshness
        SLO source — sampled even when a stalled feed yields no tick,
        so a breach keeps producing evidence; ISSUE 16)."""
        lag = self.lag_s(now)
        if lag is not None:
            obs.gauge("stream_lag_s", round(lag, 6), stream=True)
            obs.gauge(f"stream_lag_s[{self.name}]", round(lag, 6))
            obs.observe(f"stream_lag_s[{self.name}]", lag)

    def lag_s(self, now: float | None = None) -> float | None:
        """Processing lag behind the feed head: wall seconds since the
        newest CONSUMED chunk was appended by the producer (the
        ``stream_lag_s`` gauge; None before any consumption)."""
        if self._last_chunk_t is None:
            return None
        now = time.time() if now is None else now
        return max(now - float(self._last_chunk_t), 0.0)

    def _measure_row(self, res, lane: int = 0) -> dict:
        from ..io.results import batch_lane_row

        return batch_lane_row(res, lane, self.cfg.lamsteps)

    def _tick(self, now: float | None = None) -> dict:
        """One sliding-window recompute over the HBM-resident ring:
        the fixed-signature compiled fit + the incremental-ACF
        timescale proxy, emitted as one result row."""
        t0 = time.perf_counter()
        step = self._ensure_step()
        with obs.span("stream.tick", feed=self.name,
                      window_end=self.consumed):
            res = step(self.ring.window_device()[None])
        self.tick_seq += 1
        self.last_tick_at = self.consumed
        row = self._row_base(self.consumed, final=False)
        row.update(self._measure_row(res))
        hw = self.acf.halfwidth_s(self.dt)
        if hw is not None:
            row["acf_halfwidth_s"] = round(hw, 6)
        row["quarantined_chunks"] = int(sum(self.quarantined.values()))
        latency = time.perf_counter() - t0
        row["tick_latency_s"] = round(latency, 6)
        self._publish_metrics(latency,
                              time.time() if now is None else now)
        return row

    def _final_tick(self, now: float | None = None) -> dict | None:
        """The finalized feed's last word: one more full-window tick
        over the completed tail, marked ``final`` — the row the
        byte-identity acceptance gate compares against a one-shot
        batch run of the same data (re-running the warm signature is
        cheaper than special-casing "the last hop already ticked
        here").  A feed SHORTER than the window cannot execute the
        fixed signature: it runs the one-shot batch path over the
        actual samples instead (its own — warmable — signature),
        marked ``partial_window``."""
        if self.ring.full:
            row = self._tick(now=now)
            row["final"] = True
            row["name"] = f"{self.name}@final"
            return row
        # short feed: one-shot batch fit over what arrived
        from ..parallel import run_pipeline

        t0 = time.perf_counter()
        try:
            epoch = self.reader.epoch()
        except FeedError:
            return None
        ((idx, res),) = run_pipeline([epoch], self.cfg,
                                     async_exec=False)
        del idx
        self.tick_seq += 1
        self.last_tick_at = self.consumed
        row = self._row_base(self.consumed, final=True)
        row["tobs"] = epoch.nsub * self.dt
        row["partial_window"] = True
        row.update(self._measure_row(res))
        row["quarantined_chunks"] = int(sum(self.quarantined.values()))
        latency = time.perf_counter() - t0
        row["tick_latency_s"] = round(latency, 6)
        self._publish_metrics(latency,
                              time.time() if now is None else now)
        return row

    # -- telemetry ----------------------------------------------------------
    def stats(self) -> dict:
        """The per-feed heartbeat/fleet payload."""
        return {
            "feed": self.name, "window": self.window, "hop": self.hop,
            "ticks": int(self.tick_seq),
            "consumed": int(self.consumed),
            "committed": int(self.reader.total_samples),
            "finalized": self.reader.finalized,
            "quarantined": int(sum(self.quarantined.values())),
            "lag_s": (round(self.lag_s(), 3)
                      if self._last_chunk_t is not None else None),
            "tick_latency_s": ({
                "p50": round(self.tick_hist.quantile(0.50), 6),
                "p95": round(self.tick_hist.quantile(0.95), 6)}
                if self.tick_hist.n else None),
        }
