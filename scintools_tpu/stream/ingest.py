"""Append-mode dynspec feed: a durable chunk log + device ring buffer.

A FEED is a directory an observatory-side producer grows chunk-by-chunk
along the time axis while consumers (the streaming serve worker, a
notebook session) follow it live:

    feed_dir/
      MANIFEST.json          the feed identity (freqs/dt/mjd/name) and
                             the COMMITTED chunk list — one record per
                             chunk {seq, file, nt, crc, t}; rewritten
                             atomically (tmp + os.replace) per append,
                             so it is always a whole, valid JSON
      chunk_<seq>.npy        one appended [nf, nt_chunk] float32 block,
                             written tmp + os.replace — a chunk file is
                             whole or absent, never torn
      chunk_*.corrupt        quarantined bytes of an unparseable
                             orphan (forensics, like the results
                             plane's torn-tail salvage)

Durability contract (same shape as utils/segments' torn-tail rule): the
MANIFEST is the source of truth — a chunk is part of the feed the
moment its manifest record lands.  A producer crash between the chunk
rename and the manifest rewrite leaves a whole-but-uncommitted ORPHAN
chunk file; :class:`FeedWriter` reopen adopts it (it re-verifies the
bytes and commits the record) so no appended data is lost, and
quarantines unparseable orphans aside as ``.corrupt``.  Readers only
ever see committed chunks, and verify each chunk's CRC32 on read.

The consumer side keeps the live window DEVICE-RESIDENT:
:class:`Ring` holds the last W time samples in HBM and updates it with
one fixed-signature jitted roll per push (the incoming chunk is padded
onto a closed pow2 width ladder, so the whole observation executes a
handful of tiny programs) — per-tick H2D traffic is O(nf x chunk)
instead of O(nf x W).  :class:`IncrementalACF` maintains the zero
frequency-lag time-ACF cut over the same ring by adding the new
columns' pair terms and subtracting the evicted ones — O(chunk x lags
x nf) per push instead of a from-scratch O(W x lags x nf) — with a
periodic exact resync bounding float drift (the same discipline as the
NUDFT tile's phasor resync).
"""

from __future__ import annotations

import io
import json
import os
import re
import time
import zlib

import numpy as np

from .. import obs
from ..utils import fsio
from ..health import (DEFAULT_MAX_NONFINITE_FRAC,
                      DEFAULT_MAX_ZERO_BAND_FRAC)
from ..utils.log import get_logger, log_event

MANIFEST = "MANIFEST.json"
FEED_VERSION = 1
_CHUNK_RE = re.compile(r"^chunk_(\d{8})\.npy$")

# smallest padded chunk width: below this every push shares one ring
# program (mirror of buckets.VECTOR_RUNG_MIN's role for fitter inputs)
CHUNK_RUNG_MIN = 8

# incremental-ACF exact recompute cadence: bounds float drift from the
# add/subtract updates (parity vs from-scratch is test-pinned)
ACF_RESYNC_EVERY = 64


class FeedError(ValueError):
    """A structurally-invalid feed: missing/torn manifest, shape
    mismatch, or a committed chunk whose bytes fail their CRC.  A
    ``ValueError``, so the serve taxonomy classifies it poison
    (deterministic for the bytes on disk), never transient."""


def chunk_rung(n: int, minimum: int = CHUNK_RUNG_MIN) -> int:
    """Smallest pow2-ladder width >= ``n``: the padded chunk width a
    ring push canonicalises onto, so arbitrary producer chunk sizes
    execute a CLOSED set of ring-update programs."""
    if n < 1:
        raise ValueError(f"chunk_rung: need n >= 1, got {n}")
    r = max(int(minimum), 1)
    while r < n:
        r *= 2
    return r


def preflight_chunk(chunk,
                    max_nonfinite_frac: float = DEFAULT_MAX_NONFINITE_FRAC,
                    max_zero_band_frac: float = DEFAULT_MAX_ZERO_BAND_FRAC
                    ) -> list[str]:
    """Per-chunk data-quality reason codes ([] = healthy) — the
    streaming counterpart of :func:`scintools_tpu.health.
    preflight_epoch`, with the same thresholds and code spellings for
    the checks that make sense on a [nf, c] block (axes belong to the
    feed manifest, not the chunk).  Host numpy, microseconds per
    chunk."""
    dyn = np.asarray(chunk)
    if dyn.ndim != 2 or dyn.shape[0] < 2 or dyn.shape[1] < 1:
        return ["axis_shape"]
    reasons: list[str] = []
    finite = np.isfinite(dyn)
    if 1.0 - finite.mean() > max_nonfinite_frac:
        reasons.append("nonfinite")
    vals = np.where(finite, dyn, 0.0)
    if not np.any(vals):
        reasons.append("all_zero")
    elif float(np.mean(~np.any(vals != 0.0, axis=1))) \
            > max_zero_band_frac:
        reasons.append("zero_band")
    return reasons


def mask_chunk(chunk: np.ndarray) -> np.ndarray:
    """Deterministic repair of a quarantined chunk so the window stays
    continuous (bad chunks are MASKED, not fatal): non-finite samples
    become the chunk's own per-channel finite mean (refill's
    interpolation idea, chunk-local), and a chunk with nothing usable
    becomes zeros.  CHUNK-LOCAL on purpose: crash recovery rebuilds the
    ring by replaying the log, and a mask that depended on window
    history would not replay to the same bytes."""
    dyn = np.asarray(chunk, dtype=np.float32)
    finite = np.isfinite(dyn)
    if not finite.any():
        return np.zeros_like(dyn)
    if finite.all():
        return dyn
    counts = finite.sum(axis=1, keepdims=True)
    sums = np.where(finite, dyn, 0.0).sum(axis=1, keepdims=True)
    fill = np.divide(sums, counts, out=np.zeros_like(sums),
                     where=counts > 0)
    return np.where(finite, dyn, fill).astype(np.float32)


def _chunk_name(seq: int) -> str:
    return f"chunk_{seq:08d}.npy"


def _encode_chunk(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr, dtype=np.float32))
    return buf.getvalue()


def _decode_chunk(data: bytes) -> np.ndarray:
    return np.load(io.BytesIO(data), allow_pickle=False)


class FeedWriter:
    """Producer handle: append [nf, c] chunks, finalize when the
    observation ends.  Reopening an existing feed resumes it (adopting
    any whole-but-uncommitted orphan chunk a crash left behind)."""

    def __init__(self, directory: str, freqs=None, dt: float | None = None,
                 mjd: float = 50000.0, name: str | None = None):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        man = _read_manifest(directory, missing_ok=True)
        if man is None:
            if freqs is None or dt is None:
                raise ValueError("a fresh feed needs freqs= and dt= "
                                 "(the axes identity every window "
                                 "shares)")
            freqs = [float(f) for f in np.asarray(freqs).ravel()]
            if len(freqs) < 2:
                raise ValueError(f"feed needs >= 2 channels, got "
                                 f"{len(freqs)}")
            man = {"version": FEED_VERSION, "kind": "scintools-tpu-feed",
                   "name": name or os.path.basename(
                       os.path.abspath(directory)),
                   "mjd": float(mjd), "dt": float(dt), "freqs": freqs,
                   "chunks": [], "finalized": False}
            _write_manifest(directory, man)
        else:
            if freqs is not None and len(np.asarray(freqs).ravel()) \
                    != len(man["freqs"]):
                raise FeedError(
                    f"feed {directory}: reopen with {len(freqs)} "
                    f"channels, manifest has {len(man['freqs'])}")
            self._recover(man)
        self.manifest = man

    @property
    def nf(self) -> int:
        return len(self.manifest["freqs"])

    @property
    def total_samples(self) -> int:
        return sum(int(c["nt"]) for c in self.manifest["chunks"])

    def _recover(self, man: dict) -> None:
        """Adopt whole-but-uncommitted orphan chunks (producer crashed
        between the chunk rename and the manifest rewrite) in seq
        order; quarantine unparseable ones aside as ``.corrupt``.
        Renames are atomic, so an orphan is never torn — but its bytes
        are still re-verified before commit."""
        committed = {int(c["seq"]) for c in man["chunks"]}
        names = []
        try:
            names = sorted(fsio.list(self.dir))
        except OSError:
            return
        changed = False
        for fname in names:
            m = _CHUNK_RE.match(fname)
            if m is None or int(m.group(1)) in committed:
                continue
            path = os.path.join(self.dir, fname)
            try:
                data = fsio.read(path)
                arr = _decode_chunk(data)
                if arr.ndim != 2 or arr.shape[0] != len(man["freqs"]):
                    raise ValueError(f"orphan shape {arr.shape}")
            except (OSError, ValueError):
                try:
                    fsio.rename_if_absent(path, path + ".corrupt")
                except OSError:  # fault-ok: quarantined by a racer
                    pass
                log_event(get_logger(), "feed_chunk_quarantined",
                          feed=self.dir, chunk=fname)
                continue
            man["chunks"].append({"seq": int(m.group(1)), "file": fname,
                                  "nt": int(arr.shape[1]),
                                  "crc": zlib.crc32(data),
                                  "t": round(os.path.getmtime(path), 6)})
            changed = True
            log_event(get_logger(), "feed_chunk_adopted", feed=self.dir,
                      chunk=fname)
        if changed:
            man["chunks"].sort(key=lambda c: int(c["seq"]))
            _write_manifest(self.dir, man)

    def append(self, chunk) -> int:
        """Commit one [nf, c] chunk (stored float32 — the staging
        dtype).  Returns the chunk's sequence number.  Chunk bytes
        land atomically BEFORE the manifest record commits them, so a
        crash anywhere leaves either a committed chunk or a
        recoverable orphan — never a torn feed."""
        if self.manifest["finalized"]:
            raise FeedError(f"feed {self.dir} is finalized")
        arr = np.asarray(chunk)
        if arr.ndim != 2 or arr.shape[0] != self.nf or arr.shape[1] < 1:
            raise ValueError(
                f"append: expected [nf={self.nf}, c>=1], got "
                f"{arr.shape}")
        seq = (int(self.manifest["chunks"][-1]["seq"]) + 1
               if self.manifest["chunks"] else 0)
        fname = _chunk_name(seq)
        data = _encode_chunk(arr)
        path = os.path.join(self.dir, fname)
        fsio.put_atomic(path, data)
        self.manifest["chunks"].append(
            {"seq": seq, "file": fname, "nt": int(arr.shape[1]),
             "crc": zlib.crc32(data), "t": round(time.time(), 6)})
        _write_manifest(self.dir, self.manifest)
        return seq

    def finalize(self) -> None:
        """Mark the observation complete: consumers run their final
        window tick and streaming jobs COMPLETE (until then they stay
        registered, polling for more chunks)."""
        if not self.manifest["finalized"]:
            self.manifest["finalized"] = True
            _write_manifest(self.dir, self.manifest)


def _write_manifest(directory: str, man: dict) -> None:
    fsio.put_atomic(os.path.join(directory, MANIFEST),
                    json.dumps(man))


def _read_manifest(directory: str, missing_ok: bool = False):
    path = os.path.join(directory, MANIFEST)
    try:
        man = json.loads(fsio.read(path))
    except FileNotFoundError:
        if missing_ok:
            return None
        raise FeedError(f"{directory}: not a feed (no {MANIFEST})")
    # other OSErrors (transient IO on a shared feed dir) propagate:
    # they are retry evidence, not "this is not a feed"
    except ValueError as e:
        # the manifest is written atomically, so torn JSON here is
        # real corruption, not a mid-write race
        raise FeedError(f"{directory}/{MANIFEST}: invalid JSON ({e})")
    if not isinstance(man, dict) or man.get("kind") != \
            "scintools-tpu-feed" or "chunks" not in man:
        raise FeedError(f"{directory}/{MANIFEST}: not a scintools-tpu "
                        "feed manifest")
    return man


class FeedReader:
    """Consumer handle over a feed directory: committed chunks only,
    CRC-verified on read."""

    def __init__(self, directory: str):
        self.dir = directory
        self.manifest = _read_manifest(directory)

    def refresh(self) -> None:
        self.manifest = _read_manifest(self.dir)

    @property
    def nf(self) -> int:
        return len(self.manifest["freqs"])

    @property
    def dt(self) -> float:
        return float(self.manifest["dt"])

    @property
    def name(self) -> str:
        return str(self.manifest.get("name", "feed"))

    @property
    def finalized(self) -> bool:
        return bool(self.manifest.get("finalized", False))

    @property
    def total_samples(self) -> int:
        return sum(int(c["nt"]) for c in self.manifest["chunks"])

    def freqs(self) -> np.ndarray:
        return np.asarray(self.manifest["freqs"], dtype=np.float64)  # host-f64: axes identity

    def read_chunk(self, rec: dict) -> np.ndarray:
        path = os.path.join(self.dir, rec["file"])
        try:
            data = fsio.read(path)
        except FileNotFoundError as e:
            # a COMMITTED chunk's file vanished: deterministic for the
            # directory on disk (someone deleted feed data)
            raise FeedError(f"feed chunk {rec['file']}: missing ({e})")
        # any other OSError (ESTALE/EIO/NFS blip on a shared feed dir)
        # is transient evidence, NOT corruption: it propagates as-is so
        # the serve taxonomy keeps its bounded-retry path instead of
        # poisoning a healthy live observation
        if zlib.crc32(data) != int(rec["crc"]):
            raise FeedError(f"feed chunk {rec['file']}: CRC mismatch "
                            "(corrupt bytes)")
        arr = _decode_chunk(data)
        if arr.ndim != 2 or arr.shape[0] != self.nf:
            raise FeedError(f"feed chunk {rec['file']}: shape "
                            f"{arr.shape} != [nf={self.nf}, *]")
        return arr

    def chunks_since(self, sample: int):
        """Yield ``(start_sample, record)`` for every committed chunk
        whose samples begin at or after ``sample`` (the session's
        consume cursor — always chunk-aligned, so no partial
        overlaps).  Call :meth:`refresh` first to see new commits."""
        start = 0
        for rec in self.manifest["chunks"]:
            if start >= sample:
                yield start, rec
            start += int(rec["nt"])

    def times(self, n: int, start: int = 0) -> np.ndarray:
        """The feed's RELATIVE time axis for ``n`` samples: 0-based
        ``arange(n) * dt``.  Every full window shares this axis
        regardless of where it sits in the observation (the fits
        depend on the dt spacing, not the absolute offset) — which is
        exactly what keeps the window step ONE compiled signature."""
        del start  # relative by design; kept for call-site clarity
        return np.arange(n, dtype=np.float64) * self.dt  # host-f64: axes

    def epoch(self, last: int | None = None):
        """The committed feed (or its last ``last`` samples) as a
        :class:`~scintools_tpu.data.DynspecData` — the one-shot batch
        view of the same data a streaming session windows over, used
        by the byte-identity acceptance gate and the partial-window
        final fit."""
        from ..data import DynspecData

        parts = [self.read_chunk(rec) for rec in
                 self.manifest["chunks"]]
        if not parts:
            raise FeedError(f"feed {self.dir}: no committed chunks")
        dyn = np.concatenate(parts, axis=1)
        if last is not None:
            dyn = dyn[:, -int(last):]
        return DynspecData(dyn=dyn.astype(np.float64),  # host-f64: staging parity with pad_batch
                           freqs=self.freqs(),
                           times=self.times(dyn.shape[1]),
                           mjd=float(self.manifest.get("mjd", 50000.0)),
                           name=self.name)


class Ring:
    """Device-resident ring over the last W time samples.

    ``push`` transfers only the (rung-padded) incoming chunk and
    updates the HBM window with one fixed-signature jitted
    concat+dynamic-slice — no per-push recompiles beyond the closed
    pow2 chunk-width ladder, no O(W) re-staging per tick.  A host
    mirror is kept in step (pure data movement on both sides, so they
    are bit-identical) for the incremental ACF, chunk preflight and
    crash-recovery replay."""

    def __init__(self, nf: int, window: int, dtype="float32"):
        if window < 2:
            raise ValueError(f"ring window must be >= 2, got {window}")
        self.nf = int(nf)
        self.window = int(window)
        self.dtype = np.dtype(dtype)
        self._host = np.zeros((self.nf, self.window), dtype=self.dtype)
        self._dev = None
        self.count = 0            # total samples ever pushed

    @property
    def full(self) -> bool:
        return self.count >= self.window

    def _updater(self):
        import jax

        nf, W = self.nf, self.window

        def upd(win, chunk, n):
            cat = jax.numpy.concatenate([win, chunk], axis=1)
            return jax.lax.dynamic_slice(
                cat, (jax.numpy.int32(0), n), (nf, W))

        # ONE jit'd callable: jax's own cache retraces per padded
        # chunk shape, and the rung ladder keeps that set closed
        return jax.jit(upd)

    def push(self, chunk: np.ndarray) -> None:
        """Append ``c`` new samples (oldest ``c`` fall out once full).
        Chunks wider than the window keep only their last W columns."""
        arr = np.asarray(chunk, dtype=self.dtype)
        if arr.ndim != 2 or arr.shape[0] != self.nf:
            raise ValueError(f"push: expected [nf={self.nf}, c], got "
                             f"{arr.shape}")
        pushed = arr.shape[1]
        c = pushed
        if c >= self.window:
            arr = arr[:, -self.window:]
            c = self.window
        self._host = np.concatenate([self._host[:, c:], arr], axis=1)
        self.count += pushed
        self._push_device(arr, c)

    def _push_device(self, arr: np.ndarray, c: int) -> None:
        try:
            import jax
        except ImportError:  # pragma: no cover - jax is a core dep
            return
        if self._dev is None:
            self._dev = jax.numpy.asarray(
                np.zeros((self.nf, self.window), dtype=self.dtype))
        cpad = chunk_rung(c)
        padded = np.zeros((self.nf, cpad), dtype=self.dtype)
        padded[:, :c] = arr
        # the ONLY per-push H2D traffic: the rung-padded chunk (the
        # window itself stays HBM-resident across ticks)
        obs.inc("bytes_h2d", padded.nbytes)
        fn = getattr(self, "_update_fn", None)
        if fn is None:
            fn = self._update_fn = self._updater()
        self._dev = fn(self._dev, padded, np.int32(c))

    def window_device(self):
        """The HBM-resident [nf, W] window (host mirror if nothing was
        ever pushed through the device path)."""
        return self._dev if self._dev is not None else self._host

    def window_host(self) -> np.ndarray:
        return self._host

    def reset(self, host: np.ndarray, count: int) -> None:
        """Crash-recovery replay handoff: install a rebuilt host
        window (and re-stage it once — the one full-window transfer a
        resume pays)."""
        host = np.asarray(host, dtype=self.dtype)
        if host.shape != (self.nf, self.window):
            raise ValueError(f"reset: expected {(self.nf, self.window)}"
                             f", got {host.shape}")
        self._host = host.copy()
        self.count = int(count)
        self._dev = None
        try:
            import jax

            obs.inc("bytes_h2d", self._host.nbytes)
            self._dev = jax.numpy.asarray(self._host)
        except ImportError:  # pragma: no cover
            pass


class IncrementalACF:
    """Zero frequency-lag time-ACF cut over a sliding window, updated
    INCREMENTALLY: ``A[lag] = sum_{f,t} w[f,t] * w[f,t+lag]``.  A push
    of ``c`` columns subtracts the evicted columns' pair terms (from
    the pre-push window) and adds the new columns' (from the post-push
    window) — O(c x lags x nf) instead of the from-scratch
    O(W x lags x nf) — and every :data:`ACF_RESYNC_EVERY` pushes an
    exact recompute re-anchors the accumulator so float drift stays
    bounded (parity with from-scratch is test-pinned).

    This is the stream plane's cheap between-fits surface: the
    normalised cut's half-power lag (:meth:`halfwidth_s`) is the live
    timescale proxy each tick row carries beside the canonical warm
    compiled tau/dnu fit (which is never derived from this
    accumulator).  The ISSUE 17 incremental tick path generalises
    this push discipline to the fitter's own inputs —
    :class:`~scintools_tpu.stream.incremental.IncrementalCuts`
    maintains BOTH fit cuts with the same evict/add pair-sum update
    and resync cadence."""

    def __init__(self, window: int, nlags: int | None = None,
                 resync_every: int = ACF_RESYNC_EVERY):
        self.window = int(window)
        self.nlags = int(nlags if nlags is not None
                         else max(2, min(self.window // 2, 64)))
        if not 1 <= self.nlags <= self.window:
            raise ValueError(f"nlags={self.nlags} must be within the "
                             f"window ({self.window})")
        self.resync_every = int(resync_every)
        self.acf = np.zeros(self.nlags, dtype=np.float64)  # host-f64: accumulator precision
        self._pushes = 0

    @staticmethod
    def _pairs(win: np.ndarray, lags: int, cols) -> np.ndarray:
        """Pair-term sums ``sum_f win[:, j-lag] * win[:, j]`` for
        ``j`` in ``cols``, per lag (terms with j-lag < 0 drop)."""
        out = np.zeros(lags, dtype=np.float64)  # host-f64: accumulator precision
        cols = np.asarray(cols)
        for lag in range(lags):
            js = cols[cols >= lag]
            if js.size:
                out[lag] = float(np.einsum("ij,ij->", win[:, js - lag],
                                           win[:, js]))
        return out

    def compute(self, win: np.ndarray) -> np.ndarray:
        """From-scratch cut over ``win`` — the resync anchor and the
        parity oracle."""
        w = np.asarray(win, dtype=np.float64)  # host-f64: accumulator precision
        out = np.zeros(self.nlags, dtype=np.float64)  # host-f64: accumulator precision
        for lag in range(self.nlags):
            out[lag] = float(np.einsum(
                "ij,ij->", w[:, :w.shape[1] - lag] if lag else w,
                w[:, lag:] if lag else w))
        return out

    def push(self, before: np.ndarray, after: np.ndarray,
             c: int) -> None:
        """Advance over one ring push: ``before``/``after`` are the
        host windows around it, ``c`` the slide width."""
        c = min(int(c), self.window)
        self._pushes += 1
        if self._pushes % self.resync_every == 0 or c >= self.window:
            self.acf = self.compute(after)
            return
        W = self.window
        bf = np.asarray(before, dtype=np.float64)  # host-f64: accumulator precision
        af = np.asarray(after, dtype=np.float64)  # host-f64: accumulator precision
        # pairs lost with the evicted leading c columns of `before`:
        # every pair whose EARLIER member sits at i < c, i.e. whose
        # later member j = i + lag lands in [0, c + lag)
        lost = np.zeros(self.nlags, dtype=np.float64)  # host-f64: accumulator precision
        for lag in range(self.nlags):
            hi = min(c + lag, W)
            js = np.arange(lag, hi)
            if js.size:
                lost[lag] = float(np.einsum(
                    "ij,ij->", bf[:, js - lag], bf[:, js]))
        # pairs gained with the new trailing c columns of `after`
        gained = self._pairs(af, self.nlags, np.arange(W - c, W))
        self.acf = self.acf - lost + gained

    def cut(self) -> np.ndarray:
        """The current (unnormalised) time-lag cut."""
        return self.acf.copy()

    def halfwidth_s(self, dt: float) -> float | None:
        """First lag (seconds, linear-interpolated) where the
        normalised cut falls below 1/2 — the live timescale proxy.
        None while the cut is degenerate (empty/flat window)."""
        a0 = self.acf[0]
        if not np.isfinite(a0) or a0 <= 0:
            return None
        norm = self.acf / a0
        below = np.nonzero(norm < 0.5)[0]
        if below.size == 0:
            return None
        k = int(below[0])
        if k == 0:
            return 0.0
        y0, y1 = norm[k - 1], norm[k]
        frac = (y0 - 0.5) / (y0 - y1) if y0 != y1 else 0.0
        return float((k - 1 + frac) * dt)
