"""tempo2 ``.par`` pulsar-ephemeris parser.

Reference: ``read_par`` (scint_utils.py:197-249) and ``pars_to_params``
(scint_utils.py:252-278).  Values are typed (int / float / string), errors
stored as ``<KEY>_ERR``, the type recorded as ``<KEY>_TYPE``; DM-model and
fit-control keys are ignored.  RAJ/DECJ sexagesimal strings convert to
radians without astropy.
"""

from __future__ import annotations

from decimal import Decimal, InvalidOperation

import numpy as np

_IGNORE = ['DMMODEL', 'DMOFF', 'DM_', 'CM_', 'CONSTRAIN', 'JUMP', 'NITS',
           'NTOA', 'CORRECT_TROPOSPHERE', 'PLANET_SHAPIRO', 'DILATEFREQ',
           'TIMEEPH', 'MODE', 'TZRMJD', 'TZRSITE', 'TZRFRQ', 'EPHVER',
           'T2CMETHOD']


def read_par(parfile: str) -> dict:
    par: dict = {}
    with open(parfile) as fh:
        for line in fh:
            sline = line.split()
            if (not sline or line[0] == "#" or line[0:2] == "C "
                    or sline[0] in _IGNORE):
                continue
            param = sline[0]
            if param == "E":
                param = "ECC"
            val = sline[1]
            err = None
            if len(sline) == 3 and sline[2] not in ("0", "1"):
                err = sline[2].replace("D", "E")
            elif len(sline) == 4:
                err = sline[3].replace("D", "E")

            p_type = None
            try:
                val = int(val)
                p_type = "d"
            except ValueError:
                try:
                    val = float(Decimal(val.replace("D", "E")))
                    p_type = "e" if ("e" in sline[1]
                                     or "E" in sline[1].replace("D", "E")) \
                        else "f"
                except InvalidOperation:
                    p_type = "s"

            par[param] = val
            if err:
                par[param + "_ERR"] = float(err)
            if p_type:
                par[param + "_TYPE"] = p_type
    return par


def hms_to_rad(s: str) -> float:
    """Sexagesimal hour angle 'hh:mm:ss.s' -> radians."""
    sign = -1.0 if s.strip().startswith("-") else 1.0
    h, m, sec = (list(map(float, s.strip().lstrip("+-").split(":"))) + [0, 0])[:3]
    return sign * (h + m / 60 + sec / 3600) * np.pi / 12


def dms_to_rad(s: str) -> float:
    """Sexagesimal degrees 'dd:mm:ss.s' -> radians."""
    sign = -1.0 if s.strip().startswith("-") else 1.0
    d, m, sec = (list(map(float, s.strip().lstrip("+-").split(":"))) + [0, 0])[:3]
    return sign * (d + m / 60 + sec / 3600) * np.pi / 180


def pars_to_params(pars: dict, params: dict | None = None) -> dict:
    """par-dict -> flat fit-parameter dict (the lmfit-free analogue of
    scint_utils.py:252-278): numeric entries copied, RAJ/DECJ converted to
    radians.  Strings are dropped.

    For drop-in interop with scripts written against the reference's
    lmfit return type, use :func:`pars_to_lmfit_params`."""
    out = dict(params) if params else {}
    for key, value in pars.items():
        if key in ("RAJ", "RA") and isinstance(value, str):
            out["RAJ"] = hms_to_rad(value)
            dec = pars.get("DECJ", pars.get("DEC"))
            if isinstance(dec, str):
                out["DECJ"] = dms_to_rad(dec)
            continue
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            out[key] = float(value)
    return out


def pars_to_lmfit_params(pars: dict, params=None):
    """par-dict -> ``lmfit.Parameters``, matching the reference's return
    type exactly (scint_utils.py:252-278: each numeric entry added with
    ``vary=False``, RAJ/DECJ in radians) so lmfit-based user scripts port
    without edits.  Requires lmfit (not a framework dependency — this
    repo's fitters don't use it); raises ImportError with the dict-based
    alternative named when it is absent."""
    try:
        from lmfit import Parameters
    except ImportError as e:  # pragma: no cover - env without lmfit
        raise ImportError(
            "pars_to_lmfit_params requires the optional 'lmfit' package; "
            "use pars_to_params (plain dict, same values) with this "
            "framework's own fitters") from e
    out = params if params is not None else Parameters()
    for key, value in pars_to_params(pars).items():
        out.add(key, value=value, vary=False)
    return out
