from .archive import clean_archive, make_dynspec  # noqa: F401
from .adapters import (concatenate_time, from_arrays, from_matlab,  # noqa: F401
                       from_simulation)
from .parfile import pars_to_lmfit_params, pars_to_params, read_par  # noqa: F401
from .psrflux import read_psrflux, write_psrflux  # noqa: F401
from .results import (float_array_from_dict, read_dynlist,  # noqa: F401
                      read_results, results_row, write_results)
