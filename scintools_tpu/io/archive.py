"""psrchive bridge: RFI-clean an archive before making a dynspec.

Reference: ``clean_archive`` (scint_utils.py:19-56), which shells into the
optional psrchive + coast_guard stack.  Neither is installable in most
environments (they are observatory builds), so this module gates cleanly:
the function works when the stack is present and raises an actionable
error otherwise.  The rest of the framework never needs it — psrflux
files and dyn-like adapters are the supported ingest paths.
"""

from __future__ import annotations


def clean_archive(archive, template: str | None = None,
                  bandwagon: float = 0.99, channel_threshold: float = 5,
                  subint_threshold: float = 5):
    """Surgical + bandwagon RFI cleaning of a psrchive archive
    (scint_utils.py:19-56).

    ``archive`` is a loaded ``psrchive.Archive``.  Requires the external
    psrchive python bindings and coast_guard; raises ImportError with
    install guidance when absent.
    """
    try:
        from coast_guard import cleaners  # type: ignore
    except ImportError as e:  # pragma: no cover - env-dependent
        raise ImportError(
            "clean_archive needs the observatory stack: psrchive python "
            "bindings + coast_guard (https://github.com/larskuenkel/"
            "iterative_cleaner or coast_guard). Install them in your "
            "psrchive environment, or pre-clean archives and ingest "
            "psrflux dynamic spectra instead.") from e

    surgical = cleaners.load_cleaner("surgical")
    params = f"chan_numpieces=1,subint_numpieces=1,chanthresh={channel_threshold},subintthresh={subint_threshold}"
    if template is not None:
        params += f",template={template}"
    surgical.parse_config_string(params)
    surgical.run(archive)

    bandwagon_cleaner = cleaners.load_cleaner("bandwagon")
    bandwagon_cleaner.parse_config_string(
        f"badchantol={bandwagon},badsubtol=1.0")
    bandwagon_cleaner.run(archive)
    return archive


def make_dynspec(archive: str, template: str | None = None,
                 phasebin: int = 1, outdir: str | None = None) -> str:
    """Create a psrflux-format dynamic spectrum from a folded archive by
    shelling out to psrchive's ``psrflux`` (the command the reference's
    empty stub documents: ``psrflux -s [template] -e dynspec [archive]``,
    scint_utils.py:431-437 — implemented for real here, gated on the
    observatory stack like :func:`clean_archive`).

    ``archive`` is a path to a psrchive archive file.  Returns the path
    of the written ``<archive>.dynspec`` (moved into ``outdir`` when
    given — psrflux itself always writes beside the archive, so the
    relocation happens host-side rather than through version-dependent
    psrflux flags).  Requires the ``psrflux`` executable on PATH; raises
    RuntimeError with guidance otherwise.  The result loads with
    ``io.psrflux.read_psrflux``.
    """
    import os
    import shutil
    import subprocess

    if shutil.which("psrflux") is None:
        raise RuntimeError(
            "make_dynspec shells out to psrchive's `psrflux`, which is "
            "not on PATH. Install psrchive (observatory stack), or "
            "produce .dynspec files elsewhere and ingest them with "
            "io.psrflux.read_psrflux.")
    if phasebin != 1:
        raise NotImplementedError(
            "phasebin != 1 needs a pre-bscrunched archive: run "
            "`pam --setnbin <phasebin>` first (the reference stub never "
            "implemented this either, scint_utils.py:431-437)")
    cmd = ["psrflux"]
    if template is not None:
        cmd += ["-s", template]
    cmd += ["-e", "dynspec", archive]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
    except subprocess.CalledProcessError as e:
        err = (e.stderr or b"").decode(errors="replace").strip()
        raise RuntimeError(
            f"psrflux failed (exit {e.returncode}) on {archive!r}:"
            f"\n{err}") from e
    out = archive + ".dynspec"
    if not os.path.exists(out):
        raise RuntimeError(f"psrflux ran but {out!r} was not written")
    if outdir is not None:
        os.makedirs(outdir, exist_ok=True)
        dest = os.path.join(outdir, os.path.basename(out))
        shutil.move(out, dest)  # cross-filesystem-safe, unlike replace
        out = dest
    return out
