"""Results store: append-mode CSV of per-epoch measurements.

Reference: ``write_results``/``read_results``/``float_array_from_dict``
(scint_utils.py:75-131).  Schema kept compatible — base columns
``name,mjd,freq,bw,tobs,dt,df`` plus conditional ``tau,tauerr``,
``dnu,dnuerr``, ``eta,etaerr``, ``betaeta,betaetaerr`` — so existing survey
tooling can read our files.  The append-mode pattern doubles as crash-safe
partial-results checkpointing for batch runs (SURVEY.md §5 checkpoint row).
"""

from __future__ import annotations

import csv
import os

import numpy as np


_OPTIONAL = (("tau", "tauerr"), ("dnu", "dnuerr"),
             ("eta", "etaerr"), ("betaeta", "betaetaerr"))


def results_line(meta: dict) -> tuple[str, str]:
    """(header, row) strings for one reference-schema CSV row — the ONE
    formatter shared by the per-row :func:`write_results` appender and
    the store's streaming ``export_csv``, so both emit identical bytes."""
    header = "name,mjd,freq,bw,tobs,dt,df"
    row = "{name},{mjd},{freq},{bw},{tobs},{dt},{df}".format(**meta)
    for a, b in _OPTIONAL:
        if a in meta and meta[a] is not None:
            header += f",{a},{b}"
            row += f",{meta[a]},{meta.get(b)}"
    return header, row


def write_results(filename: str, meta: dict) -> None:
    """Append one row.  ``meta`` must carry name/mjd/freq/bw/tobs/dt/df and
    may carry any of the optional measurement pairs."""
    header, row = results_line(meta)
    with open(filename, "a") as fh:
        if not os.path.exists(filename) or os.stat(filename).st_size == 0:
            fh.write(header + "\n")
        fh.write(row + "\n")


def results_row(d, scint=None, arc=None) -> dict:
    """Build a write_results row from DynspecData + optional fit results."""
    meta = dict(name=d.name, mjd=d.mjd, freq=d.freq, bw=d.bw, tobs=d.tobs,
                dt=d.dt, df=d.df)
    if scint is not None:
        meta.update(tau=float(scint.tau), tauerr=float(scint.tauerr),
                    dnu=float(scint.dnu), dnuerr=float(scint.dnuerr))
    if arc is not None:
        key = "betaeta" if arc.lamsteps else "eta"
        meta[key] = float(arc.eta)
        meta[key + "err"] = float(arc.etaerr)
        # parabola-vertex fit error — the conditioning signal
        # (docs/migrating.md: down-weight epochs with etaerr2 > |eta|).
        # Store/full-CSV only: write_results' _OPTIONAL filter keeps the
        # reference CSV schema unchanged.  getattr: duck-typed arc
        # results (reference-style objects) may predate the field.
        err2 = getattr(arc, "etaerr2", None)
        if err2 is not None:
            meta[key + "err2"] = float(err2)
    return meta


def batch_lane_row(res, lane: int, lamsteps: bool) -> dict:
    """Measurement columns for ONE lane of a batched ``PipelineResult``
    — the single source of truth shared by the CLI's batched engine and
    the resident serve worker, so a served survey's rows are
    bit-identical to a direct ``run_pipeline`` run's."""
    row: dict = {}
    if res.scint is not None:
        row.update(
            tau=float(np.asarray(res.scint.tau)[lane]),
            tauerr=float(np.asarray(res.scint.tauerr)[lane]),
            dnu=float(np.asarray(res.scint.dnu)[lane]),
            dnuerr=float(np.asarray(res.scint.dnuerr)[lane]))
    if res.arc is not None:
        key = "betaeta" if lamsteps else "eta"
        row[key] = float(np.asarray(res.arc.eta)[lane])
        row[key + "err"] = float(np.asarray(res.arc.etaerr)[lane])
        # the parabola-vertex fit error (conditioning signal) — store
        # rows only; write_results' _OPTIONAL filter keeps the CSV on
        # the reference schema
        row[key + "err2"] = float(np.asarray(res.arc.etaerr2)[lane])
        if res.arc.eta_left is not None:
            # per-arm values go to the store rows only as well
            for arm in ("eta_left", "etaerr_left",
                        "eta_right", "etaerr_right"):
                row[arm] = float(np.asarray(getattr(res.arc, arm))[lane])
    if res.tilt is not None:
        # store rows only, like the per-arm values
        row["tilt"] = float(np.asarray(res.tilt)[lane])
        row["tilterr"] = float(np.asarray(res.tilterr)[lane])
    return row


def row_fit_values(row: dict) -> list:
    """The fitted quantities a quarantine decision looks at: a NaN in
    any of them marks the lane a FAILED fit (retried on resume), as the
    per-file loop does via exceptions."""
    return [v for k, v in row.items()
            if k in ("tau", "dnu", "eta", "betaeta", "tilt")]


def read_results(filename: str) -> dict:
    """CSV -> dict of string lists (scint_utils.py:111-124)."""
    with open(filename) as fh:
        data = list(csv.reader(fh, delimiter=","))
    keys = data[0]
    out: dict = {k: [] for k in keys}
    for row in data[1:]:
        for ii, v in enumerate(row):
            out[keys[ii]].append(v)
    return out


def float_array_from_dict(dictionary: dict, key: str) -> np.ndarray:
    return np.array([float(v) for v in dictionary[key]])


def read_dynlist(file_path: str) -> list[str]:
    """File-of-filenames reader (scint_utils.py:66-72)."""
    with open(file_path) as fh:
        return fh.read().splitlines()
