"""Results store: append-mode CSV of per-epoch measurements.

Reference: ``write_results``/``read_results``/``float_array_from_dict``
(scint_utils.py:75-131).  Schema kept compatible — base columns
``name,mjd,freq,bw,tobs,dt,df`` plus conditional ``tau,tauerr``,
``dnu,dnuerr``, ``eta,etaerr``, ``betaeta,betaetaerr`` — so existing survey
tooling can read our files.  The append-mode pattern doubles as crash-safe
partial-results checkpointing for batch runs (SURVEY.md §5 checkpoint row).
"""

from __future__ import annotations

import csv
import os

import numpy as np


_OPTIONAL = (("tau", "tauerr"), ("dnu", "dnuerr"),
             ("eta", "etaerr"), ("betaeta", "betaetaerr"))


def write_results(filename: str, meta: dict) -> None:
    """Append one row.  ``meta`` must carry name/mjd/freq/bw/tobs/dt/df and
    may carry any of the optional measurement pairs."""
    header = "name,mjd,freq,bw,tobs,dt,df"
    row = "{name},{mjd},{freq},{bw},{tobs},{dt},{df}".format(**meta)
    for a, b in _OPTIONAL:
        if a in meta and meta[a] is not None:
            header += f",{a},{b}"
            row += f",{meta[a]},{meta.get(b)}"
    with open(filename, "a") as fh:
        if not os.path.exists(filename) or os.stat(filename).st_size == 0:
            fh.write(header + "\n")
        fh.write(row + "\n")


def results_row(d, scint=None, arc=None) -> dict:
    """Build a write_results row from DynspecData + optional fit results."""
    meta = dict(name=d.name, mjd=d.mjd, freq=d.freq, bw=d.bw, tobs=d.tobs,
                dt=d.dt, df=d.df)
    if scint is not None:
        meta.update(tau=float(scint.tau), tauerr=float(scint.tauerr),
                    dnu=float(scint.dnu), dnuerr=float(scint.dnuerr))
    if arc is not None:
        key = "betaeta" if arc.lamsteps else "eta"
        meta[key] = float(arc.eta)
        meta[key + "err"] = float(arc.etaerr)
        # parabola-vertex fit error — the conditioning signal
        # (docs/migrating.md: down-weight epochs with etaerr2 > |eta|).
        # Store/full-CSV only: write_results' _OPTIONAL filter keeps the
        # reference CSV schema unchanged.  getattr: duck-typed arc
        # results (reference-style objects) may predate the field.
        err2 = getattr(arc, "etaerr2", None)
        if err2 is not None:
            meta[key + "err2"] = float(err2)
    return meta


def read_results(filename: str) -> dict:
    """CSV -> dict of string lists (scint_utils.py:111-124)."""
    with open(filename) as fh:
        data = list(csv.reader(fh, delimiter=","))
    keys = data[0]
    out: dict = {k: [] for k in keys}
    for row in data[1:]:
        for ii, v in enumerate(row):
            out[keys[ii]].append(v)
    return out


def float_array_from_dict(dictionary: dict, key: str) -> np.ndarray:
    return np.array([float(v) for v in dictionary[key]])


def read_dynlist(file_path: str) -> list[str]:
    """File-of-filenames reader (scint_utils.py:66-72)."""
    with open(file_path) as fh:
        return fh.read().splitlines()
