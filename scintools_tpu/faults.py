"""Deterministic fault injection + the infra-error taxonomy (ISSUE 5).

The north star is a resident service on shared TPU pods, where the
dominant failures are *infrastructure* faults — an XLA
``RESOURCE_EXHAUSTED`` on an oversized chunk, a preempted device, a
prefetch thread dying mid-survey — not per-job data pathologies.  Those
faults are recoverable by construction (production JAX stacks treat
preemption as a scheduling event, not an error), but a recovery path
that has never executed is a recovery path that does not work.  This
module makes every such path *provable*: named injection sites threaded
through the driver, scheduler, compile cache and serve loop raise
deterministic faults on the exact call you ask for, so a tier-1 chaos
test can demand "OOM on chunk 3" and assert the self-healing behaviour
(driver backoff, serve requeue-without-budget-burn) byte-for-byte.

Design constraints:

1. **Zero overhead when empty.**  Every site calls :func:`check`, whose
   disarmed cost is ONE dict lookup (``_SPECS.get(site)`` on an empty
   dict); no env read, no lock, no allocation.  Tier-1 asserts the
   default path is bit-identical with sites threaded.
2. **Deterministic.**  A :class:`FaultSpec` fires on exactly the
   ``at_call``-th invocation of its site (per-process counters), for
   exactly ``times`` consecutive calls, then disarms.  No randomness —
   chaos tests replay identically.
3. **Config/env-driven.**  ``SCINT_FAULTS="driver.chunk_execute:oom@3"``
   arms sites in a subprocess (comma-separated
   ``site:kind[@at_call[xtimes]]`` specs, parsed once by
   :func:`install_env`); tests arm them directly with :func:`inject` /
   the :func:`injected` context manager.

The module also owns the error TAXONOMY the serve loop classifies by:

* :class:`TransientError` — infrastructure: succeeds on retry/another
  worker (OOM, lease races, preemption, injected infra faults).  The
  queue requeues these WITHOUT burning the bounded retry budget
  (``JobQueue.fail(transient=True)``).
* :class:`PoisonError` — deterministic: same input, same failure, every
  time (bad config, corrupt file).  Goes straight to the existing
  bounded-retry -> ``failed/`` poison path.

:func:`classify_error` maps arbitrary exceptions onto the taxonomy
(``"transient" | "poison" | "unknown"``); unknown keeps today's
solo-retry semantics so classification can only *improve* behaviour.

See docs/reliability.md for the fault model and the site catalog.
"""

from __future__ import annotations

import contextlib
import dataclasses
import errno
import os
import threading

from . import obs

ENV_VAR = "SCINT_FAULTS"

# ---------------------------------------------------------------------------
# taxonomy
# ---------------------------------------------------------------------------


class TransientError(Exception):
    """An infrastructure failure expected to succeed on retry (OOM,
    preemption, lease race, injected infra fault).  The serve queue
    requeues these without decrementing the bounded retry budget."""


class PoisonError(Exception):
    """A deterministic failure: the same input fails the same way every
    time (bad config, corrupt epoch).  Takes the bounded-retry ->
    ``failed/`` poison path."""


class InjectedFault(TransientError):
    """An armed :class:`FaultSpec` firing (transient by default: the
    registry simulates infrastructure faults)."""


class InjectedPoison(PoisonError):
    """An armed ``kind="poison"`` fault firing (for proving the poison
    path stays bounded under classification)."""


class InjectedCrash(BaseException):
    """An armed storage-crash kind firing (``torn_write`` /
    ``crash_before_rename`` / ``crash_after_rename``).  Deliberately a
    ``BaseException``: this is not an error to classify but a crash
    directive — the fsio driver verbs catch it at the injection site,
    perform the spec'd partial work, and hard-exit (``os._exit``), so
    the process dies exactly as a SIGKILL at that boundary would.  An
    escaped one (armed at a non-fsio site) kills the process, which is
    the honest semantics for a crash kind."""

    #: faults kind -> the driver's crash choreography
    CRASH = {"torn_write": "torn", "crash_before_rename": "before",
             "crash_after_rename": "after"}

    def __init__(self, kind: str, detail: str):
        super().__init__(detail)
        self.kind = kind
        self.crash = self.CRASH[kind]


# substrings of XLA runtime OOM surfaces (jaxlib raises XlaRuntimeError
# with the gRPC status name embedded; allocator failures say
# "out of memory").  Deliberately NO bare "OOM" token: a filename like
# ZOOM_55.dynspec inside a FileNotFoundError must not read as device
# memory exhaustion.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


def is_oom_error(exc: BaseException) -> bool:
    """Whether ``exc`` is a device memory exhaustion — an
    ``XlaRuntimeError``/``RESOURCE_EXHAUSTED`` (or an injected
    ``kind="oom"`` fault, which carries the same marker)."""
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


# substrings marking lease-race / preemption surfaces as transient
_TRANSIENT_MARKERS = ("lease expired", "preempt", "UNAVAILABLE",
                      "DEADLINE_EXCEEDED")


def classify_error(exc: BaseException) -> str:
    """``"transient"`` / ``"poison"`` / ``"unknown"``.

    Transient: the taxonomy classes above, device OOM (the driver's
    chunk backoff may still fail at the floor chunk — another worker
    or a quieter pod succeeds), and lease-race/preemption markers.
    Poison: :class:`PoisonError` and the constructor-validation errors
    a deterministic bad config raises (``ValueError``/``TypeError``
    from ``make_pipeline``/``config_from_opts``).  Everything else is
    unknown and keeps the existing bounded solo-retry semantics.

    Precedence: explicit taxonomy types first, then the deterministic
    TYPES (``ValueError``/``TypeError``) — a validation error whose
    message happens to contain an infra marker (a path, a quoted
    config value) must still poison — and the message-substring infra
    markers only for everything else."""
    if isinstance(exc, TransientError):
        return "transient"
    if isinstance(exc, PoisonError):
        return "poison"
    if isinstance(exc, (ValueError, TypeError)):
        return "poison"
    if isinstance(exc, OSError) and exc.errno in (errno.ENOSPC,
                                                  errno.EDQUOT):
        # a full disk/quota recovers after compaction or space
        # recovery — burning the bounded retry budget would poison a
        # perfectly good job for an infrastructure condition
        return "transient"
    if is_oom_error(exc):
        return "transient"
    if any(m in str(exc) for m in _TRANSIENT_MARKERS):
        return "transient"
    return "unknown"


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class FaultSpec:
    """One armed fault.

    ``kind`` selects the raised exception: ``"oom"`` (an
    OOM-marker-carrying :class:`InjectedFault`, so ``is_oom_error``
    and the driver's backoff treat it exactly like a real XLA
    RESOURCE_EXHAUSTED), ``"transient"`` (:class:`InjectedFault`),
    ``"poison"`` (:class:`InjectedPoison`), ``"oserror"`` (an
    :class:`OSError`, for rename/IO race sites whose handlers catch
    exactly that), ``"error"`` (a plain :class:`RuntimeError` —
    lands in the *unknown* classification bucket), the errno storage
    kinds ``"enospc"``/``"eio"`` (an :class:`OSError` carrying that
    errno, so the caller's existing narrow handlers AND
    :func:`classify_error` see the real thing), or the storage CRASH
    kinds ``"torn_write"``/``"crash_before_rename"``/
    ``"crash_after_rename"`` (an :class:`InjectedCrash` directive the
    fsio driver verbs translate into partial work + ``os._exit`` —
    arm these only at ``fsio.*`` sites).

    ``at_call`` is 1-based: the fault fires on that invocation of its
    site and for ``times`` consecutive calls after it, then disarms.

    Unknown kinds are rejected at construction — a typo'd
    ``SCINT_FAULTS`` spec (``oomx2``, ``posion``) must fail loudly,
    never silently inject a differently-classified exception that
    exercises the wrong recovery path.
    """

    KINDS = ("oom", "transient", "poison", "oserror", "error",
             "torn_write", "crash_before_rename", "crash_after_rename",
             "enospc", "eio")

    kind: str = "transient"
    at_call: int = 1
    times: int = 1
    message: str = ""
    calls: int = 0  # mutated by check(); per-process

    def __post_init__(self):
        if self.kind not in self.KINDS:
            raise ValueError(
                f"FaultSpec: unknown kind {self.kind!r} (expected one "
                f"of {'/'.join(self.KINDS)})")
        if self.at_call < 1 or self.times < 1:
            raise ValueError(
                f"FaultSpec: at_call/times must be >= 1, got "
                f"{self.at_call}/{self.times}")

    def build_exc(self, site: str) -> BaseException:
        detail = self.message or f"injected {self.kind} at {site} " \
                                 f"(call {self.calls})"
        if self.kind == "oom":
            return InjectedFault(f"RESOURCE_EXHAUSTED: {detail}")
        if self.kind == "poison":
            return InjectedPoison(detail)
        if self.kind == "oserror":
            return OSError(detail)
        if self.kind == "enospc":
            return OSError(errno.ENOSPC, detail)
        if self.kind == "eio":
            return OSError(errno.EIO, detail)
        if self.kind in InjectedCrash.CRASH:
            return InjectedCrash(self.kind, detail)
        if self.kind == "error":
            return RuntimeError(detail)
        return InjectedFault(detail)


# The closed catalog of injection sites threaded through the code
# (docs/reliability.md has the table).  parse_env validates against it
# so a typo'd SCINT_FAULTS site fails LOUDLY instead of arming a site
# no code ever checks (the chaos drive would pass vacuously — clean
# vs faulted identical because nothing fired).  The programmatic
# inject() API stays unvalidated on purpose: tests exercise the
# registry with synthetic sites.  Extend this tuple when threading a
# new faults.check() site.
KNOWN_SITES = ("driver.chunk_execute", "driver.admit_chunk",
               "schedule.prefetch",
               "compile_cache.load", "queue.claim_rename",
               "worker.load", "worker.batch_execute", "worker.poll",
               "pool.spawn", "pool.drain",
               # blocks StreamSession.poll's consumption loop (lag
               # still sampled): the injected freshness breach the
               # SLO smoke gate drives (ISSUE 16)
               "stream.poll",
               # the storage driver verbs (ISSUE 20): one site per
               # verb, armed with the errno kinds (enospc/eio) or the
               # crash kinds (torn_write/crash_before_rename/
               # crash_after_rename) to enumerate recovery from every
               # durable-plane mutation boundary
               "fsio.put", "fsio.append", "fsio.read", "fsio.list",
               "fsio.delete", "fsio.rename")

# site -> FaultSpec.  EMPTY in production: check()'s disarmed cost is
# the one dict lookup the acceptance criteria demand.  Armed only by
# inject()/install_env(); mutations guarded by _ARM_LOCK (check()'s
# counter increment rides the GIL — chaos tests are single-arming).
_SPECS: dict[str, FaultSpec] = {}
_ARM_LOCK = threading.Lock()
_ENV_INSTALLED = False


def check(site: str) -> None:
    """The injection hook.  Disarmed: one dict lookup.  Armed for
    ``site``: counts the call and raises the spec's exception when the
    window [at_call, at_call+times) is hit (``faults_injected``
    counter, so a trace proves the fault actually fired).  A spec past
    its window is REMOVED — the site truly disarms (``active()`` stops
    reporting it, the dict-miss fast path is restored for a
    long-running worker)."""
    spec = _SPECS.get(site)
    if spec is None:
        return
    spec.calls += 1
    if spec.at_call <= spec.calls:
        if spec.calls >= spec.at_call + spec.times:
            clear(site)
            return
        if spec.calls == spec.at_call + spec.times - 1:
            clear(site)  # last call of the window: fire AND disarm
        obs.inc("faults_injected")
        obs.inc(f"faults_injected[{site}]")
        raise spec.build_exc(site)


def inject(site: str, spec: FaultSpec) -> None:
    """Arm ``spec`` at ``site`` (replacing any previous spec there)."""
    with _ARM_LOCK:
        _SPECS[site] = spec


def clear(site: str | None = None) -> None:
    """Disarm one site, or every site (``site=None``)."""
    with _ARM_LOCK:
        if site is None:
            _SPECS.clear()
        else:
            _SPECS.pop(site, None)


def active() -> dict[str, FaultSpec]:
    """The currently-armed sites (a copy; for test introspection)."""
    with _ARM_LOCK:
        return dict(_SPECS)


@contextlib.contextmanager
def injected(site: str, spec: FaultSpec):
    """Scoped arming for tests::

        with faults.injected("driver.chunk_execute",
                             faults.FaultSpec(kind="oom", at_call=3)):
            run_pipeline(...)
    """
    inject(site, spec)
    try:
        yield spec
    finally:
        clear(site)


def parse_env(value: str) -> dict[str, FaultSpec]:
    """Parse ``SCINT_FAULTS``: comma-separated
    ``site:kind[@at_call[xtimes]]`` specs, e.g.
    ``driver.chunk_execute:oom@3`` or
    ``worker.batch_execute:transient@1x2``.  Unparseable entries —
    including unknown kinds (``oomx2``, a typo) — raise: a chaos
    harness must fail loudly, not silently inject the wrong fault."""
    out: dict[str, FaultSpec] = {}
    for entry in value.split(","):
        entry = entry.strip()
        if not entry:
            continue
        site, sep, rest = entry.partition(":")
        if not sep or not site:
            raise ValueError(f"{ENV_VAR}: bad entry {entry!r} "
                             "(want site:kind[@at_call[xtimes]])")
        if site not in KNOWN_SITES:
            raise ValueError(
                f"{ENV_VAR}: unknown site {site!r} (known sites: "
                f"{', '.join(KNOWN_SITES)}) — a typo'd site would arm "
                "nothing and the chaos run would pass vacuously")
        kind, at_call, times = rest, 1, 1
        if "@" in rest:
            kind, _, tail = rest.partition("@")
            try:
                if "x" in tail:
                    a, _, t = tail.partition("x")
                    at_call, times = int(a), int(t)
                else:
                    at_call = int(tail)
            except ValueError:
                raise ValueError(
                    f"{ENV_VAR}: bad entry {entry!r} (non-integer "
                    "at_call/times; want site:kind[@at_call[xtimes]])")
        if not kind:
            raise ValueError(f"{ENV_VAR}: bad entry {entry!r} "
                             "(empty kind)")
        try:
            out[site] = FaultSpec(kind=kind, at_call=at_call,
                                  times=times)
        except ValueError as e:
            raise ValueError(f"{ENV_VAR}: bad entry {entry!r}: {e}")
    return out


def install_env(force: bool = False) -> int:
    """Arm the faults named by ``SCINT_FAULTS`` (idempotent per process
    unless ``force``).  Returns the number of armed sites.  Called from
    the CLI entrypoint so subprocess chaos drives work; a library user
    embedding the driver calls it explicitly (or uses inject())."""
    global _ENV_INSTALLED
    if _ENV_INSTALLED and not force:
        return len(_SPECS)
    value = os.environ.get(ENV_VAR, "")
    if not value.strip():
        _ENV_INSTALLED = True
        return 0
    specs = parse_env(value)  # raises before the latch: a caller that
    # catches, fixes os.environ, and retries must not find env arming
    # permanently disabled by the failed first attempt
    _ENV_INSTALLED = True
    with _ARM_LOCK:
        _SPECS.update(specs)
    return len(specs)
