"""Analytic FLOP / HBM-byte model of the batched pipeline, for MFU and
roofline accounting (round-3 requirement: performance judged against
silicon capability, not only against one CPU process).

The reference publishes no performance model at all (SURVEY.md §6); this
module is the TPU-native framework's own accounting.  The counts are
*analytic* — derived from the algorithms, not measured by a profiler — and
deliberately conservative:

* FFTs: the standard ``5 N log2 N`` flops per length-``N`` complex
  transform (real transforms at half that), the universal FFT accounting
  convention used by FFTW's own benchmark reporting.
* Matmuls / einsums: ``2 M N K``.
* Elementwise chains: a small constant per element, stated per stage.
* HBM bytes: one read + one write of each stage's dominant arrays at the
  stage's compute dtype (f32 / complex64) — a LOWER bound on traffic
  (XLA fusion can only reduce, never increase, the modelled passes).

When the caller can supply XLA's own per-execution cost analysis for
the exact compiled step (``roofline_record(measured=...)`` — wired
through ``obs.instrument_jit`` and bench.py), the MEASURED counts are
preferred for every achieved/MFU/roofline figure and the analytic model
becomes the sanity column (``measured_vs_model`` ratios): perf claims
are then grounded in the program XLA actually built, not in a hand
model of it.

MFU here = achieved model-flops/s divided by the chip's published peak
(bf16 systolic peak by default — the GENEROUS denominator, so the quoted
MFU is conservative).  Peaks are resolved from ``jax.devices()[0]
.device_kind`` against a table of published per-chip numbers, overridable
via ``SCINT_PEAK_TFLOPS`` / ``SCINT_PEAK_GBS`` for hardware not listed.
"""

from __future__ import annotations

import math
import os
from typing import Any

__all__ = [
    "pipeline_epoch_model",
    "device_peaks",
    "measure_host_peaks",
    "roofline_record",
    "PEAKS_BY_KIND",
]


def _next_pow2_2x(n: int) -> int:
    return int(2 ** (math.ceil(math.log2(n)) + 1))


def _cfft(n: int) -> float:
    """Flops of one length-n complex FFT (5 n log2 n)."""
    return 5.0 * n * math.log2(n)


def pipeline_epoch_model(nf: int, nt: int, *, lamsteps: bool = True,
                         numsteps: int = 2000, lm_steps: int = 20,
                         scint_cuts: str = "matmul",
                         fit_arc: bool = True,
                         fit_scint: bool = True,
                         fft_lens: str = "pow2") -> dict:
    """Per-epoch flop/byte counts for the bench pipeline configuration.

    Returns ``{stage: {"flops": F, "bytes": B}, ..., "total": {...}}``.
    Stage models (one nf x nt epoch; padded FFT lengths nrfft/ncfft
    follow ``fft_lens`` — "pow2" is ops/sspec.py's next-pow2*2 default,
    "fast" the 5-smooth composite knob):

    lam    freq->lambda resample as the batched pipeline executes it
           (parallel.driver.lambda_resample_matrix): the natural-spline
           solve is amortised host-side into a dense [nlam, nf] weight
           matrix, so the per-epoch device work is ONE matmul,
           2 nlam nf nt with nlam ~= nf.
    sspec  full complex fft2 on [nrfft, ncfft] (two 1-D passes) + ~15
           elementwise ops/element (window, prewhiten 4-tap, |.|^2,
           postdark divide, log10).
    scint  ACF central cuts: "matmul" = two Gram einsums (2 nf^2 nt +
           2 nt^2 nf, the MXU route); "fft" = padded 1-D real-FFT
           correlations both axes.  Then lm_steps fixed LM iterations over
           the nf+nt residual points (~40 flops/point/step incl. jacobian
           columns and the 4x4 normal solve).
    arc    norm_sspec fixed-shape fitter: bilinear row-resample gather +
           delay scrunch over R = nrfft/2 rows x numsteps bins (~8
           flops/sample); traffic dominated by the [R, numsteps] gather.
    """
    if fft_lens == "pow2":
        nrfft, ncfft = _next_pow2_2x(nf), _next_pow2_2x(nt)
    else:
        from ..ops.sspec import fft_lens as _lens

        nrfft, ncfft = _lens(nf, nt, fft_lens)
    out: dict[str, dict[str, float]] = {}

    if lamsteps:
        out["lam"] = {"flops": 2.0 * nf * nf * nt,
                      "bytes": 2.0 * 4 * nf * nt + 4.0 * nf * nf}

    # sspec: two complex 1-D FFT passes over the padded grid + elementwise
    fft2 = ncfft * _cfft(nrfft) + nrfft * _cfft(ncfft)
    elem = 15.0 * nrfft * ncfft + 8.0 * nf * nt
    # traffic: read f32 input once, two r+w complex64 passes over the grid
    sspec_bytes = 4.0 * nf * nt + 2 * 2 * 8.0 * nrfft * ncfft
    out["sspec"] = {"flops": fft2 + elem, "bytes": sspec_bytes}

    if fit_scint:
        if scint_cuts == "matmul":
            cuts = 2.0 * nf ** 2 * nt + 2.0 * nt ** 2 * nf
        else:  # padded 1-D real-FFT correlations, both axes, fwd+inv
            cuts = nt * 2 * 0.5 * _cfft(2 * nf) + nf * 2 * 0.5 * _cfft(2 * nt)
        lm = lm_steps * 40.0 * (nf + nt)
        out["scint"] = {"flops": cuts + lm,
                        "bytes": 2.0 * 4 * nf * nt + 4.0 * 4 * (nf + nt)}

    if fit_arc:
        R = nrfft // 2
        out["arc"] = {"flops": 8.0 * R * numsteps,
                      "bytes": 3.0 * 4 * R * numsteps}

    total_f = sum(v["flops"] for v in out.values())
    total_b = sum(v["bytes"] for v in out.values())
    out["total"] = {"flops": total_f, "bytes": total_b}
    return out


# Published per-chip peaks: (dense peak TFLOP/s [bf16 systolic for TPUs,
# the generous MFU denominator], HBM GB/s).  Sources: Google Cloud TPU
# system-architecture pages / chip announcement specs.
PEAKS_BY_KIND: dict[str, tuple[float, float]] = {
    "TPU v2": (45.0, 700.0),
    "TPU v3": (123.0, 900.0),
    "TPU v4": (275.0, 1228.0),
    "TPU v5 lite": (197.0, 819.0),
    "TPU v5e": (197.0, 819.0),
    "TPU v5p": (459.0, 2765.0),
    "TPU v6 lite": (918.0, 1640.0),
    "TPU v6e": (918.0, 1640.0),
}


def device_peaks(device: Any = None) -> dict:
    """Resolve {name, peak_tflops, peak_gbs, source} for the attached
    accelerator.  Env overrides SCINT_PEAK_TFLOPS / SCINT_PEAK_GBS win;
    unknown hardware without overrides yields None peaks (MFU is then
    omitted rather than invented)."""
    kind = ""
    if device is None:
        try:
            import jax

            device = jax.devices()[0]
        except Exception:
            device = None
    if device is not None:
        kind = str(getattr(device, "device_kind", "") or "")

    peak_tf = peak_gb = None
    source = "unknown device kind; set SCINT_PEAK_TFLOPS/SCINT_PEAK_GBS"
    for key, (tf, gb) in PEAKS_BY_KIND.items():
        if key.lower() in kind.lower():
            peak_tf, peak_gb = tf, gb
            source = f"published per-chip spec for {key}"
            break
    if os.environ.get("SCINT_PEAK_TFLOPS"):
        peak_tf = float(os.environ["SCINT_PEAK_TFLOPS"])
        source = "SCINT_PEAK_TFLOPS override"
    if os.environ.get("SCINT_PEAK_GBS"):
        peak_gb = float(os.environ["SCINT_PEAK_GBS"])
    return {"device_kind": kind or None, "peak_tflops": peak_tf,
            "peak_gbs": peak_gb, "source": source}


def measure_host_peaks(matmul_n: int = 1024, copy_mb: int = 256,
                       iters: int = 3) -> dict:
    """MEASURED peaks of the host CPU (the honest denominator for the
    bench's cpu-fallback path, where quoting TPU spec-sheet peaks would
    be meaningless and quoting nothing hides the efficiency question —
    round-4 requirement: every headline record carries %-of-roofline).

    Peak flops: best of ``iters`` f32 ``matmul_n``^3 GEMMs (BLAS — the
    fastest thing this host can do, the same generous-denominator
    convention as the TPUs' bf16 systolic peak).  Peak bandwidth: best-of
    round-trip ``np.copyto`` streaming rate over a ``copy_mb`` MB buffer
    (read + write counted, matching the model's traffic convention)."""
    import numpy as np
    import time

    rng = np.random.default_rng(0)
    a = rng.standard_normal((matmul_n, matmul_n)).astype(np.float32)
    b = rng.standard_normal((matmul_n, matmul_n)).astype(np.float32)
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        (a @ b).sum()
        best = min(best, time.perf_counter() - t0)
    peak_tf = 2.0 * matmul_n ** 3 / best / 1e12

    n = copy_mb * (1 << 20) // 4
    src = np.empty(n, dtype=np.float32)
    src[:] = 1.0  # materialise pages: an untouched zeros buffer maps to
    # the kernel's shared zero page and the read half would come from
    # cache, overstating bandwidth ~2x (measured on this host)
    dst = np.empty_like(src)
    dst[:] = 0.0
    best_c = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        np.copyto(dst, src)
        best_c = min(best_c, time.perf_counter() - t0)
    peak_gb = 2.0 * src.nbytes / best_c / 1e9
    return {"device_kind": "host-cpu", "peak_tflops": round(peak_tf, 4),
            "peak_gbs": round(peak_gb, 1),
            "source": (f"measured on this host (best-of-{iters}: "
                       f"f32 {matmul_n}^3 GEMM, {copy_mb} MB memcpy r+w)")}


def roofline_record(rate_epochs_per_s: float, nf: int, nt: int,
                    peaks: dict | None = None,
                    measured: dict | None = None, **model_kw) -> dict:
    """Achieved GFLOP/s, GB/s, arithmetic intensity and %-of-peak for a
    measured pipeline rate.  ``peaks=None`` resolves the attached device;
    pass ``peaks={}`` to skip peak lookup (model-only record).

    ``measured`` takes XLA's own per-EPOCH cost-analysis counts
    (``{"flops": F, "bytes_accessed": B}`` — see
    ``obs.xla_cost_analysis`` / bench.py) and, when given, is PREFERRED
    over the analytic model for every achieved/MFU/roofline figure: the
    model's byte count is a deliberate lower bound, so a model-based
    roofline_pct overstates nothing but also cannot see padding or
    fusion reality.  The record then carries both (``measured_*``
    fields + ``measured_vs_model`` ratios) and names its source in
    ``roofline_source``, so every future perf claim states what it was
    computed from.

    With both peaks known the record also carries ``roofline_pct``: the
    achieved flop rate as a percentage of the roofline-implied ceiling
    for this pipeline's arithmetic intensity,
    ``min(peak_flops, AI * peak_bandwidth)`` — the single number the
    round-3 verdict asked every headline to defend (100% = the hardware
    bound for this program shape; ``bound`` names which side binds)."""
    model = pipeline_epoch_model(nf, nt, **model_kw)
    f, b = model["total"]["flops"], model["total"]["bytes"]
    if peaks is None:
        peaks = device_peaks()
    rec = {
        "model_gflop_per_epoch": round(f / 1e9, 3),
        "model_gbytes_per_epoch": round(b / 1e9, 3),
        "achieved_gflops": round(rate_epochs_per_s * f / 1e9, 3),
        "achieved_gbytes_s": round(rate_epochs_per_s * b / 1e9, 3),
        "arithmetic_intensity_flop_per_byte": round(f / b, 1),
        "per_stage_gflop": {k: round(v["flops"] / 1e9, 3)
                            for k, v in model.items() if k != "total"},
        # per-stage BYTES split beside the flop split: on a bandwidth-
        # bound step (BENCH_r05: 6 % of roofline, AI ~ 6) the traffic
        # attribution is the one that names the next fusion target —
        # the fused-vs-chain sspec claim reads from this column
        "per_stage_gbytes": {k: round(v["bytes"] / 1e9, 3)
                             for k, v in model.items() if k != "total"},
    }
    # measured (cost_analysis) counts trump the model when available
    f_eff, b_eff, source = f, b, "analytic model (lower-bound bytes)"
    if measured:
        mf = measured.get("flops")
        mb = measured.get("bytes_accessed")
        if mf and mb:
            f_eff, b_eff = float(mf), float(mb)
            source = "measured (XLA cost_analysis)"
            rec["measured_gflop_per_epoch"] = round(f_eff / 1e9, 3)
            rec["measured_gbytes_per_epoch"] = round(b_eff / 1e9, 3)
            rec["achieved_gflops"] = round(
                rate_epochs_per_s * f_eff / 1e9, 3)
            rec["achieved_gbytes_s"] = round(
                rate_epochs_per_s * b_eff / 1e9, 3)
            rec["arithmetic_intensity_flop_per_byte"] = round(
                f_eff / b_eff, 1)
            rec["measured_vs_model"] = {"flops": round(f_eff / f, 2),
                                        "bytes": round(b_eff / b, 2)}
    rec["roofline_source"] = source
    peak_tf = peaks.get("peak_tflops")
    peak_gb = peaks.get("peak_gbs")
    if peak_tf:
        rec["mfu_pct"] = round(
            100.0 * rate_epochs_per_s * f_eff / (peak_tf * 1e12), 4)
    if peak_gb:
        rec["hbm_pct"] = round(
            100.0 * rate_epochs_per_s * b_eff / (peak_gb * 1e9), 4)
    if peak_tf and peak_gb:
        ai = f_eff / b_eff
        ceiling = min(peak_tf * 1e12, ai * peak_gb * 1e9)
        rec["roofline_pct"] = round(
            100.0 * rate_epochs_per_s * f_eff / ceiling, 2)
        rec["roofline_bound"] = ("compute"
                                 if peak_tf * 1e12 <= ai * peak_gb * 1e9
                                 else "bandwidth")
    if peaks:
        rec["peaks"] = {k: peaks.get(k) for k in
                        ("device_kind", "peak_tflops", "peak_gbs", "source")}
    return rec
