"""Tracing / profiling hooks (SURVEY.md §5 "tracing" row).

The reference's only instrumentation is wall-clock prints around file
loads (dynspec.py:104,153-155).  Here:

* :class:`StageTimers` — accumulating per-stage wall-clock timers for the
  pipeline driver and CLI, with device-sync-aware timing (``block=`` calls
  ``jax.block_until_ready`` before stopping the clock, so jit async
  dispatch doesn't fake speed).
* :func:`trace_annotation` — names a region in the device profile
  (``jax.profiler.TraceAnnotation``); no-op without jax.
* :func:`profile_trace` — context manager around ``jax.profiler.trace``
  writing a TensorBoard-loadable device trace.
"""

from __future__ import annotations

import contextlib
import time
from collections import OrderedDict


class StageTimers:
    """Accumulate wall time and call counts per named stage.

    >>> timers = StageTimers()
    >>> with timers.stage("sspec"):
    ...     out = compute()
    >>> timers.summary()
    {'sspec': {'calls': 1, 'total_s': ..., 'mean_s': ...}}
    """

    def __init__(self):
        self._acc: "OrderedDict[str, list]" = OrderedDict()

    @contextlib.contextmanager
    def stage(self, name: str, block=None):
        """Time a region.  Pass ``block=value_or_pytree`` to synchronise on
        device completion of that value before stopping the clock.

        Regions are mirrored as ``stage.<name>`` spans into
        :mod:`scintools_tpu.obs` when tracing is enabled, so CLI stage
        timers land in ``--trace`` files alongside the pipeline spans.
        """
        from .. import obs

        sp = obs.span("stage." + name)
        sp.__enter__()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            try:
                if block is not None:
                    try:
                        import jax

                        jax.block_until_ready(block)
                    except ImportError:  # pragma: no cover
                        pass
            finally:
                # the span must close and the stage must accumulate even
                # when the device sync raises (async failure surfacing
                # here), or the leaked span corrupts every later span
                # path on this thread
                dt = time.perf_counter() - t0
                sp.__exit__(None, None, None)
                tot, n = self._acc.get(name, (0.0, 0))
                self._acc[name] = (tot + dt, n + 1)

    def summary(self) -> dict:
        return {k: {"calls": n, "total_s": round(tot, 6),
                    "mean_s": round(tot / n, 6)}
                for k, (tot, n) in self._acc.items()}

    def report(self) -> str:
        lines = [f"{k:>24s}  {v['calls']:5d} calls  "
                 f"{v['total_s']:9.3f} s total  {v['mean_s']:9.4f} s/call"
                 for k, v in self.summary().items()]
        return "\n".join(lines)


def trace_annotation(name: str):
    """Named region in the device profiler timeline; no-op without jax."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except ImportError:  # pragma: no cover
        return contextlib.nullcontext()


@contextlib.contextmanager
def profile_trace(logdir: str):
    """Capture a device trace viewable in TensorBoard/XProf.

    >>> with profile_trace("/tmp/trace"):
    ...     step(batch).block_until_ready()
    """
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def xprof_bracket(logdir: str | None):
    """The shared ``--xprof DIR`` implementation (CLI process/serve and
    bench): :func:`profile_trace` around the measure window, recorded
    as a ``devmem.xprof`` obs span so the capture window itself shows
    in ``trace report``.  nullcontext when ``logdir`` is falsy."""
    if not logdir:
        return contextlib.nullcontext()
    from .. import obs

    @contextlib.contextmanager
    def bracket():
        with obs.span("devmem.xprof", dir=logdir):
            with profile_trace(logdir):
                yield
    return bracket()
