"""Structured logging (SURVEY.md §5 "metrics/logging" row).

The reference reports progress with bare ``print()`` calls scattered
through compute methods (dynspec.py:107,155; scint_sim.py:62-69).  Here a
single std-``logging`` channel with a key=value formatter, so batch
drivers and the CLI emit grep-able, timestamped events without touching
the compute layers.

``SCINTOOLS_TPU_LOG`` sets the default level (name or number, e.g.
``DEBUG`` / ``10``); an explicit ``level=`` argument wins, and — unlike
the original ``if not logger.handlers`` guard, which silently ignored
``level`` on every call after the first — the level is (re)applied on
every :func:`get_logger` call that passes one.
"""

from __future__ import annotations

import logging
import os

_FORMAT = "%(asctime)s %(levelname)s %(name)s %(message)s"


def _default_level():
    env = os.environ.get("SCINTOOLS_TPU_LOG", "").strip()
    if not env:
        return logging.INFO
    if env.isdigit():
        return int(env)
    return logging.getLevelName(env.upper()) \
        if isinstance(logging.getLevelName(env.upper()), int) else logging.INFO


def get_logger(name: str = "scintools_tpu", level=None) -> logging.Logger:
    """The shared key=value channel.  ``level=None`` means "leave an
    already-configured logger alone; initialise a fresh one from
    ``SCINTOOLS_TPU_LOG`` (default INFO)"."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
        logger.addHandler(h)
        logger.setLevel(_default_level() if level is None else level)
        logger.propagate = False
    elif level is not None:
        # always honour an explicit level, first call or not
        logger.setLevel(level)
    return logger


def log_event(logger: logging.Logger, event: str, *,
              level: int = logging.INFO, **fields) -> None:
    """Emit ``event key=value ...`` (floats compacted).  ``level=`` routes
    chatty per-operation events (e.g. per-epoch refill/zap stats) to
    DEBUG so they only appear under ``SCINTOOLS_TPU_LOG=DEBUG``."""
    if not logger.isEnabledFor(level):
        return
    parts = [event]
    for k, v in fields.items():
        if isinstance(v, float):
            parts.append(f"{k}={v:.6g}")
        else:
            parts.append(f"{k}={v}")
    logger.log(level, " ".join(parts))
