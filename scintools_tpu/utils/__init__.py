"""Observability + persistence utilities (SURVEY.md §5 subsystems)."""

from .log import get_logger, log_event  # noqa: F401
from .misc import (is_valid, load_pickle, remove_duplicates,  # noqa: F401
                   save_pickle)
from .segments import SegmentStore  # noqa: F401
from .store import ResultsStore, content_key, seed_range_pending  # noqa: F401
from .timing import StageTimers, profile_trace, trace_annotation  # noqa: F401
