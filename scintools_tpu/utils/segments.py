"""Append-only columnar segment files: the million-epoch results plane.

The one-JSON-file-per-row :class:`~scintools_tpu.utils.store.
ResultsStore` is the scale ceiling for synthetic campaigns and the
serve fleet (ROADMAP item 5): a 10^6-epoch run means 10^6 tiny files
and an O(N) listdir-heavy gather at campaign end.  This module is the
replacement sink — results stream out *while* the campaign runs, the
way real-time Fourier-domain search pipelines emit candidates against
a live stream (arXiv:1804.05335) — with the axis/metadata discipline
kept first-class through the refactor (FFTArray, arXiv:2508.03697):
every write-once idempotency key survives as segment metadata, and the
footer carries the column union so schema travels with the bytes.

Wire format of one segment (everything little-endian)::

    "SCSEG01\\n"                              8-byte file magic
    [ <len:u32> <crc32:u32> <payload> ]*      record blocks, payload =
                                              JSON [key, record] utf-8
    <footer payload>                          JSON, columnar index:
                                              {v, rows, keys[], offsets[],
                                               lengths[], columns[],
                                               bloom{m,k,bits}}
    <flen:u32> <fcrc32:u32> "SCSEGFTR"        16-byte trailer

A segment is written to ``<name>.open`` (blocks first, footer last)
and atomically renamed to ``<name>.seg`` at seal time, so readers only
ever index sealed files.  A writer SIGKILLed between block append and
footer flush leaves a ``.open`` file behind: :meth:`SegmentStore.
refresh` detects the dead writer (pid embedded in the name), salvages
the checksum-valid block prefix into a fresh sealed segment, and
quarantines the original aside as ``.corrupt`` — exactly the row
store's torn-row contract, so the keys lost in the torn tail simply
re-execute and nothing is ever duplicated (reads dedup by key,
newest segment first).

Lookup is newest-segment-first: each footer ships a small bloom filter
over its keys, so a ``has``/``get`` probe touches only segments that
plausibly hold the key (and the footer's exact key index settles it
without reading any block).  A background ``compact`` merges small
segments into one (`serve`'s ``compact`` job kind), keeping the
per-lookup segment count bounded over a long campaign.

Observability (names registered in obs/names.py): counters
``segment_flushes`` / ``segment_rows`` / ``segment_bytes`` /
``compactions`` / ``segments_compacted`` / ``segments_quarantined`` /
``segment_salvaged_rows``.
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import time
import zlib
from typing import Iterable, Sequence

from . import fsio

MAGIC = b"SCSEG01\n"
FOOTER_MAGIC = b"SCSEGFTR"
SEGMENT_VERSION = 1
_HDR = struct.Struct("<II")          # (payload length, crc32)
_TRAILER = struct.Struct("<II")      # (footer length, footer crc32)
_TRAILER_LEN = _TRAILER.size + len(FOOTER_MAGIC)
# sanity bound on one block: a results row is ~1 KB of JSON; anything
# claiming >64 MB is a torn/foreign length field, not a record
MAX_BLOCK_BYTES = 1 << 26

# bloom sizing: ~10 bits/key, 4 probes ≈ 1-2 % false-positive rate —
# a false positive costs one dict miss, never a wrong answer
_BLOOM_BITS_PER_KEY = 10
_BLOOM_K = 4

OPEN_EXT = ".open"
SEG_EXT = ".seg"
CORRUPT_EXT = ".corrupt"
# a live writer's .open file is left alone; one whose pid is dead (or
# unparseable) is salvaged once it is older than the MIN age below,
# and a live-pid file older than the larger grace is treated as
# abandoned (pid reuse after a reboot)
OPEN_GRACE_S = 300.0
# pid liveness is HOST-LOCAL: on a shared filesystem another host's
# in-flight writer looks "dead" to os.kill here.  A flush seals in
# well under a second, so a dead-looking .open younger than this is
# plausibly a remote writer mid-append — leave it for the next pass
OPEN_SALVAGE_MIN_AGE_S = 5.0
# how old a memoised directory mtime must be before an equal re-stat
# is trusted as "nothing changed" (the racy-stat guard in
# SegmentStore.refresh): one second covers every filesystem timestamp
# granularity in practice (the same bound git's racily-clean index
# rule assumes)
_MTIME_SETTLE_NS = 1_000_000_000


class SegmentError(ValueError):
    """A segment's bytes fail structural validation (bad magic, torn
    block, checksum mismatch, unreadable footer)."""


def _obs():
    from .. import obs

    return obs


# ---------------------------------------------------------------------------
# block + bloom primitives
# ---------------------------------------------------------------------------


def encode_block(key: str, record: dict) -> bytes:
    """One length-prefixed, checksummed record block."""
    payload = json.dumps([key, record]).encode("utf-8")
    return _HDR.pack(len(payload), zlib.crc32(payload)) + payload


def _bloom_indices(key: str, m: int, k: int):
    """Double-hashing bloom probe positions for ``key`` (blake2b split
    into two 64-bit halves — stable across processes and versions)."""
    h = hashlib.blake2b(key.encode("utf-8"), digest_size=16).digest()
    a = int.from_bytes(h[:8], "little")
    b = int.from_bytes(h[8:], "little") or 1
    for i in range(k):
        yield (a + i * b) % m


def _bloom_build(keys: Sequence[str]) -> dict:
    m = max(64, _BLOOM_BITS_PER_KEY * len(keys))
    m += (-m) % 8                      # whole bytes
    bits = bytearray(m // 8)
    for key in keys:
        for idx in _bloom_indices(key, m, _BLOOM_K):
            bits[idx >> 3] |= 1 << (idx & 7)
    return {"m": m, "k": _BLOOM_K, "bits": bytes(bits).hex()}


def _bloom_maybe(bloom: dict | None, key: str) -> bool:
    if not bloom:
        return True                    # no filter -> cannot rule out
    try:
        m, k = int(bloom["m"]), int(bloom["k"])
        bits = bytes.fromhex(bloom["bits"])
    except (KeyError, TypeError, ValueError):
        return True
    if m <= 0 or k <= 0 or len(bits) * 8 < m:
        return True
    return all(bits[i >> 3] & (1 << (i & 7))
               for i in _bloom_indices(key, m, k))


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------

_SEQ = 0


def _segment_basename() -> str:
    """Fresh segment stem: ``seg-<17-digit µs stamp>-<pid>-<seq>`` —
    name-sortable by creation time (the newest-first read order), pid
    for dead-writer detection, per-process seq for same-µs distinctness."""
    global _SEQ
    _SEQ += 1
    stamp = time.time_ns() // 1000
    return f"seg-{stamp:017d}-{os.getpid()}-{_SEQ:04d}"


def segment_pid(fname: str) -> int | None:
    """The writer pid embedded in a segment filename, or None."""
    parts = os.path.basename(fname).split(".")[0].split("-")
    if len(parts) == 4 and parts[0] == "seg" and parts[2].isdigit():
        return int(parts[2])
    return None


class SegmentAppender:
    """Low-level writer of ONE segment: blocks appended in call order
    to the ``.open`` file, footer + atomic rename at :meth:`seal`.
    :meth:`SegmentStore.append` drives it; tests drive it directly to
    stage crash states (a SIGKILL between :meth:`add` and :meth:`seal`
    is exactly the torn-tail shape ``refresh`` must salvage)."""

    def __init__(self, directory: str, base: str | None = None):
        os.makedirs(directory, exist_ok=True)
        base = base or _segment_basename()
        self.path_open = os.path.join(directory, base + OPEN_EXT)
        self.path_seal = os.path.join(directory, base + SEG_EXT)
        self._fh = open(self.path_open, "wb")
        fsio.append(self._fh, MAGIC)
        self._keys: list[str] = []
        self._offsets: list[int] = []
        self._lengths: list[int] = []
        self._columns: set[str] = set()

    def add(self, key: str, record: dict) -> None:
        block = encode_block(key, record)
        self._offsets.append(self._fh.tell())
        self._lengths.append(len(block))
        self._keys.append(str(key))
        self._columns.update(str(c) for c in record)
        fsio.append(self._fh, block)

    def seal(self) -> tuple[str, int]:
        """Write the columnar footer, fsync-free atomic rename to
        ``.seg``.  Returns ``(sealed path, total bytes)``."""
        footer = json.dumps({
            "v": SEGMENT_VERSION, "rows": len(self._keys),
            "keys": self._keys, "offsets": self._offsets,
            "lengths": self._lengths,
            "columns": sorted(self._columns),
            "bloom": _bloom_build(self._keys),
            "created_at": round(time.time(), 6),
        }).encode("utf-8")
        fsio.append(self._fh, footer
                    + _TRAILER.pack(len(footer), zlib.crc32(footer))
                    + FOOTER_MAGIC)
        self._fh.close()
        size = os.path.getsize(self.path_open)
        fsio.rename_if_absent(self.path_open, self.path_seal)
        return self.path_seal, size

    def abort(self) -> None:
        try:
            self._fh.close()
        finally:
            try:
                fsio.delete(self.path_open)
            except OSError:  # fault-ok: best-effort cleanup of our tmp
                pass


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------


def read_footer(path: str) -> dict:
    """Parse + checksum-verify a sealed segment's footer; raises
    :class:`SegmentError` on any structural problem."""
    try:
        with open(path, "rb") as fh:
            head = fh.read(len(MAGIC))
            if head != MAGIC:
                raise SegmentError(f"{path}: bad segment magic")
            fh.seek(-_TRAILER_LEN, os.SEEK_END)
            tail = fh.read(_TRAILER_LEN)
            if len(tail) != _TRAILER_LEN \
                    or tail[_TRAILER.size:] != FOOTER_MAGIC:
                raise SegmentError(f"{path}: missing footer trailer")
            flen, fcrc = _TRAILER.unpack(tail[:_TRAILER.size])
            fh.seek(-(_TRAILER_LEN + flen), os.SEEK_END)
            footer_bytes = fh.read(flen)
    except OSError as e:
        raise SegmentError(f"{path}: unreadable ({e})") from e
    if len(footer_bytes) != flen or zlib.crc32(footer_bytes) != fcrc:
        raise SegmentError(f"{path}: footer checksum mismatch")
    try:
        footer = json.loads(footer_bytes)
    except ValueError as e:
        raise SegmentError(f"{path}: footer not JSON") from e
    if not isinstance(footer, dict) \
            or footer.get("v") != SEGMENT_VERSION:
        raise SegmentError(f"{path}: unsupported footer version")
    return footer


def scan_blocks(path: str) -> tuple[list[tuple[str, dict]], bool]:
    """Sequentially decode record blocks from the start of ``path``
    (header magic first), verifying each checksum; stops at the first
    torn/invalid block OR at a valid footer trailer.  Returns
    ``(valid rows in append order, clean)`` where ``clean`` is True
    only when the file ends in a checksum-valid footer — the salvage
    scanner for ``.open`` leftovers and corrupt sealed files."""
    rows: list[tuple[str, dict]] = []
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as fh:
            if fh.read(len(MAGIC)) != MAGIC:
                return rows, False
            while True:
                pos = fh.tell()
                hdr = fh.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    return rows, False          # torn header / EOF
                length, crc = _HDR.unpack(hdr)
                if length > MAX_BLOCK_BYTES:
                    # garbage length field — OR the start of the
                    # footer, whose JSON head reads as a huge u32;
                    # "clean" iff the trailer at EOF checks out
                    return rows, _footer_at(fh, pos, size)
                payload = fh.read(length)
                if len(payload) < length \
                        or zlib.crc32(payload) != crc:
                    return rows, _footer_at(fh, pos, size)
                try:
                    key, rec = json.loads(payload)
                except (ValueError, TypeError):
                    return rows, _footer_at(fh, pos, size)
                if not isinstance(rec, dict):
                    return rows, _footer_at(fh, pos, size)
                rows.append((str(key), rec))
    except OSError:
        return rows, False


def _footer_at(fh, pos: int, size: int) -> bool:
    """Whether the bytes from ``pos`` to EOF are a checksum-valid
    footer (payload + trailer)."""
    if size - pos < _TRAILER_LEN:
        return False
    fh.seek(size - _TRAILER_LEN)
    tail = fh.read(_TRAILER_LEN)
    if tail[_TRAILER.size:] != FOOTER_MAGIC:
        return False
    flen, fcrc = _TRAILER.unpack(tail[:_TRAILER.size])
    if pos + flen + _TRAILER_LEN != size:
        return False
    fh.seek(pos)
    return zlib.crc32(fh.read(flen)) == fcrc


class Segment:
    """One sealed segment's in-memory index: key -> (offset, length)
    from the columnar footer, plus the bloom filter for cheap negative
    probes.  Blocks are only read on :meth:`get`."""

    __slots__ = ("path", "rows", "columns", "_index", "_bloom")

    def __init__(self, path: str, footer: dict):
        self.path = path
        keys = footer.get("keys") or []
        offsets = footer.get("offsets") or []
        lengths = footer.get("lengths") or []
        if not (len(keys) == len(offsets) == len(lengths)):
            raise SegmentError(f"{path}: ragged footer index")
        self.rows = len(keys)
        self.columns = tuple(footer.get("columns") or ())
        self._index = {str(k): (int(o), int(n))
                       for k, o, n in zip(keys, offsets, lengths)}
        self._bloom = footer.get("bloom")

    @classmethod
    def load(cls, path: str) -> "Segment":
        return cls(path, read_footer(path))

    def keys(self):
        return self._index.keys()

    def maybe_contains(self, key: str) -> bool:
        return _bloom_maybe(self._bloom, key)

    def has(self, key: str) -> bool:
        return self.maybe_contains(key) and key in self._index

    def get(self, key: str, fh=None) -> dict | None:
        loc = self._index.get(key)
        if loc is None:
            return None
        offset, length = loc
        own = fh is None
        if own:
            fh = open(self.path, "rb")
        try:
            fh.seek(offset)
            block = fh.read(length)
        finally:
            if own:
                fh.close()
        if len(block) != length:
            raise SegmentError(f"{self.path}: short block for {key}")
        plen, crc = _HDR.unpack(block[:_HDR.size])
        payload = block[_HDR.size:]
        if plen != len(payload) or zlib.crc32(payload) != crc:
            raise SegmentError(
                f"{self.path}: block checksum mismatch for {key}")
        k, rec = json.loads(payload)
        return rec


# ---------------------------------------------------------------------------
# the directory of segments
# ---------------------------------------------------------------------------


class SegmentStore:
    """All sealed segments under one directory, indexed newest-first.

    * ``append(rows)`` seals ONE new segment per call — the flush unit,
      so a campaign of B epochs lands O(flushes) files, not O(B);
    * ``has``/``get`` probe newest segment first (bloom, then the exact
      footer index), so a freshly-flushed row is visible immediately;
    * ``refresh`` is mtime-gated (one ``stat`` on the no-change fast
      path — the poll-loop cost of ``SurveyClient.wait``) and salvages
      dead writers' ``.open`` leftovers on the way;
    * ``compact`` merges every sealed segment into one, newest row
      winning per key (rows for one key are deterministic duplicates
      under the at-least-once serve contract, so either choice is
      byte-identical).
    """

    def __init__(self, directory: str):
        self.dir = directory
        self._segments: list[Segment] = []   # newest first
        self._names: set[str] = set()
        self._mtime: int | None = None
        # when the memoised scan was TAKEN (wall clock): the mtime
        # gate below only trusts an equal re-stat when the scan
        # postdates the mtime tick by more than the settle window —
        # a scan racing the tick could have missed a same-tick seal
        self._scan_ns: int | None = None
        self._handles: dict[str, object] = {}
        # union of every indexed segment's keys: the O(1) membership
        # probe under the write path's per-row dedup check (a bloom
        # scan over N segments per put grows linearly with campaign
        # progress — the very shape this plane retires).  None = stale,
        # rebuilt lazily; segment ADDS update it incrementally
        self._keys: set[str] | None = None

    # -- index maintenance -------------------------------------------------
    def refresh(self, force: bool = False) -> None:
        """Re-sync the in-memory index with the directory iff its mtime
        moved (or ``force``): load new sealed segments, drop vanished
        ones, and salvage any ``.open`` file whose writer is dead."""
        try:
            mtime = os.stat(self.dir).st_mtime_ns
        except OSError:
            self._segments, self._names = [], set()
            self._mtime = None
            self._scan_ns = None
            self._close_handles()
            return
        # racy-stat guard (git's racily-clean-index rule): an unchanged
        # mtime only proves nothing changed when the memoised SCAN was
        # taken more than one timestamp-granularity window AFTER the
        # mtime tick — a scan racing the tick could have missed a
        # same-tick seal(), which would otherwise stay invisible to
        # every gated read until some later write moved the directory
        # clock (observed as a tier-1 flake on coarse-mtime runtimes).
        # A racy scan rescans; the rescan re-stamps _scan_ns, so the
        # gate re-closes one settled read later.
        if (not force and mtime == self._mtime
                and self._scan_ns is not None
                and self._scan_ns - mtime > _MTIME_SETTLE_NS):
            return
        self._salvage_dead_open()
        try:
            names = {n for n in fsio.list(self.dir)
                     if n.endswith(SEG_EXT)}
        except OSError:
            names = set()
        if names != self._names:
            removed = False
            for seg in list(self._segments):
                if os.path.basename(seg.path) not in names:
                    self._segments.remove(seg)
                    self._drop_handle(seg.path)
                    removed = True
            have = {os.path.basename(s.path) for s in self._segments}
            for name in names - have:
                path = os.path.join(self.dir, name)
                try:
                    seg = Segment.load(path)
                except SegmentError:
                    self._quarantine(path)
                    continue
                self._segments.append(seg)
                if self._keys is not None:
                    self._keys.update(seg.keys())
            if removed:
                self._keys = None      # rebuild lazily (rare path)
            self._segments.sort(key=lambda s: os.path.basename(s.path),
                                reverse=True)
            self._names = {os.path.basename(s.path)
                           for s in self._segments}
        try:
            self._mtime = os.stat(self.dir).st_mtime_ns
            self._scan_ns = time.time_ns()
        except OSError:
            self._mtime = None
            self._scan_ns = None

    def _salvage_dead_open(self) -> None:
        try:
            opens = [n for n in fsio.list(self.dir)
                     if n.endswith(OPEN_EXT)]
        except OSError:
            return
        now = time.time()
        for name in opens:
            path = os.path.join(self.dir, name)
            pid = segment_pid(name)
            if pid == os.getpid():
                continue                    # our own in-flight append
            # live local pid: mid-append unless far past any flush
            # (pid reuse).  Dead/foreign pid: still wait a short MIN
            # age — liveness probes don't cross hosts, and a remote
            # writer's flush seals in well under the threshold
            grace = (OPEN_GRACE_S
                     if pid is not None and _pid_alive(pid)
                     else OPEN_SALVAGE_MIN_AGE_S)
            try:
                if now - os.path.getmtime(path) < grace:
                    continue
            except OSError:
                continue
            self._salvage(path)

    def _salvage(self, path: str) -> None:
        """Recover the checksum-valid block prefix of a torn segment
        into a sealed one AT THE ORIGINAL NAME POSITION (stem + ``s``
        sorts immediately after the torn original), then quarantine
        the original aside as ``.corrupt`` (same contract as the row
        store's torn rows: the bytes survive for forensics, the
        lost-tail keys re-execute, and scans stop re-parsing the same
        torn file).  Sealing under a FRESH stamp here would resurrect
        stale values — newest-wins scans resolve duplicate keys by
        name order, and a salvage that runs after the key was
        re-written (a crashed writer's re-driven successor, then a
        late fsck) must not advance the old rows past the new.  Seal
        first, quarantine second: a crash mid-salvage leaves the torn
        original for the next pass, never a row loss."""
        rows, clean = scan_blocks(path)
        obs = _obs()
        if rows:
            base = os.path.basename(path).rsplit(".", 1)[0] + "s"
            app = SegmentAppender(self.dir, base=base)
            try:
                for key, rec in rows:
                    app.add(key, rec)
                app.seal()
            except Exception:
                app.abort()
                raise
            obs.inc("segment_salvaged_rows", len(rows))
        self._quarantine(path)
        from .log import get_logger, log_event

        log_event(get_logger(), "segment_quarantined", path=path,
                  salvaged_rows=len(rows), clean_footer=clean)

    def _quarantine(self, path: str) -> None:
        _obs().inc("segments_quarantined")
        self._drop_handle(path)
        try:
            fsio.rename_if_absent(path, path + CORRUPT_EXT)
        except OSError:  # fault-ok: already quarantined by a racer
            pass

    # -- handles (reused across export's many random reads) ---------------
    # bounded LRU: a long un-compacted survey can hold thousands of
    # sealed segments — caching one fd per segment touched would walk
    # straight into ulimit -n.  dict preserves insertion order; a hit
    # re-inserts, so the first entry is always the least recent.
    MAX_HANDLES = 64

    def _handle(self, seg: Segment):
        fh = self._handles.pop(seg.path, None)
        if fh is None:
            while len(self._handles) >= self.MAX_HANDLES:
                self._drop_handle(next(iter(self._handles)))
            fh = open(seg.path, "rb")
        self._handles[seg.path] = fh
        return fh

    def _drop_handle(self, path: str) -> None:
        fh = self._handles.pop(path, None)
        if fh is not None:
            try:
                fh.close()
            except OSError:  # fault-ok: close of a vanished file
                pass

    def _close_handles(self) -> None:
        for path in list(self._handles):
            self._drop_handle(path)

    # -- writes ------------------------------------------------------------
    def append(self, rows: Iterable[tuple[str, dict]]) -> str | None:
        """Seal ONE new segment holding ``rows`` (the flush unit).
        Returns the sealed path, or None for an empty flush."""
        rows = list(rows)
        if not rows:
            return None
        app = SegmentAppender(self.dir)
        try:
            for key, rec in rows:
                app.add(key, rec)
            path, size = app.seal()
        except Exception:
            app.abort()
            raise
        obs = _obs()
        obs.inc("segment_flushes")
        obs.inc("segment_rows", len(rows))
        obs.inc("segment_bytes", size)
        # index our own segment in place so the rows are queryable
        # immediately, but DON'T trust the post-seal mtime as "synced":
        # a concurrent writer may have sealed in the same window, and
        # stamping the newer mtime here would mask its segment from
        # every read until some later write — leave _mtime unset so
        # the next read re-lists once
        try:
            self._segments.insert(0, Segment.load(path))
            self._names.add(os.path.basename(path))
            if self._keys is not None:
                self._keys.update(k for k, _ in rows)
        except (SegmentError, OSError):
            pass
        self._mtime = None
        return path

    def compact(self, min_segments: int = 2) -> dict:
        """Merge every sealed segment into one (rows sorted by key,
        newest wins per duplicate), then unlink the inputs.  A crash
        between seal and unlink leaves deterministic duplicates that
        reads dedup; a concurrent ``append`` lands a segment outside
        the input set and survives untouched."""
        self.refresh(force=True)
        inputs = list(self._segments)
        if len(inputs) < max(int(min_segments), 2):
            return {"compacted": 0, "rows": 0,
                    "segments": len(inputs)}
        winners: dict[str, Segment] = {}
        for seg in reversed(inputs):        # oldest -> newest wins
            for key in seg.keys():
                winners[key] = seg
        app = SegmentAppender(self.dir)
        try:
            for key in sorted(winners):
                seg = winners[key]
                app.add(key, seg.get(key, fh=self._handle(seg)))
            path, size = app.seal()
        except Exception:
            app.abort()
            raise
        for seg in inputs:
            self._drop_handle(seg.path)
            try:
                fsio.delete(seg.path)
            except OSError:  # fault-ok: a racing compactor got there
                pass
        obs = _obs()
        obs.inc("compactions")
        obs.inc("segments_compacted", len(inputs))
        self.refresh(force=True)
        return {"compacted": len(inputs), "rows": len(winners),
                "segments": len(self._segments),
                "bytes": size, "path": os.path.basename(path)}

    # -- reads -------------------------------------------------------------
    def _key_set(self) -> set[str]:
        if self._keys is None:
            ks: set[str] = set()
            for seg in self._segments:
                ks.update(seg.keys())
            self._keys = ks
        return self._keys

    def has(self, key: str) -> bool:
        self.refresh()
        return key in self._key_set()

    def get(self, key: str) -> dict | None:
        self.refresh()
        for seg in self._segments:
            if not seg.has(key):
                continue
            try:
                return seg.get(key, fh=self._handle(seg))
            except (SegmentError, ValueError):
                # STRUCTURAL corruption evidence (checksum/format):
                # fails loudly ONCE — quarantine + salvage of the
                # valid rest — then the lost keys re-execute like
                # torn rows
                self._drop_handle(seg.path)
                self._salvage(seg.path)
                self.refresh(force=True)
                return self.get(key)
            except OSError:
                # NEVER corruption evidence: transient IO (EMFILE,
                # NFS hiccup, EIO) must not destroy durable rows.  A
                # VANISHED file is the benign compaction race — the
                # key lives in the merged segment; re-sync and retry.
                # Anything else propagates to the caller.
                self._drop_handle(seg.path)
                if os.path.exists(seg.path):
                    raise
                self.refresh(force=True)
                return self.get(key)
        return None

    def keys(self) -> set[str]:
        self.refresh()
        return set(self._key_set())    # copy: callers mutate freely

    def iter_sorted_items(self):
        """(key, record) in sorted key order, one entry per key
        (newest segment wins) — the streaming gather under
        ``export_csv``.  O(total keys) pointers in memory, never the
        records themselves; block reads go through per-segment cached
        handles so the gather is one sequential-ish sweep."""
        self.refresh()
        winners: dict[str, Segment] = {}
        for seg in reversed(self._segments):     # oldest -> newest wins
            for key in seg.keys():
                winners[key] = seg
        for key in sorted(winners):
            seg = winners[key]
            try:
                yield key, seg.get(key, fh=self._handle(seg))
            except (SegmentError, ValueError):
                # structural corruption only — see get()'s contract
                self._drop_handle(seg.path)
                self._salvage(seg.path)
                self.refresh(force=True)
                rec = self.get(key)
                if rec is not None:
                    yield key, rec
            except OSError:
                self._drop_handle(seg.path)
                if os.path.exists(seg.path):
                    raise
                self.refresh(force=True)
                rec = self.get(key)
                if rec is not None:
                    yield key, rec

    def segment_files(self) -> list[str]:
        self.refresh()
        return sorted(os.path.basename(s.path) for s in self._segments)

    def stats(self) -> dict:
        self.refresh()
        return {"segments": len(self._segments),
                "rows": sum(s.rows for s in self._segments),
                "bytes": sum(_size(s.path) for s in self._segments)}


def _size(path: str) -> int:
    try:
        return os.path.getsize(path)
    except OSError:
        return 0


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True                     # EPERM etc: someone owns it
    return True
