"""Resumable results store (SURVEY.md §5 "checkpoint / resume" row).

The reference's closest thing to checkpointing is its append-mode CSV
(scint_utils.py:75-108): a killed batch run can resume because finished
rows are already on disk.  This store makes that pattern explicit and
crash-safe:

* one JSON file per epoch under ``dir/``, keyed by a content hash of the
  input (file bytes or array) + the processing config, written atomically
  (tmp + rename) so partial writes can't corrupt the store;
* ``pending()`` filters a work list down to what is not yet done — the
  resume path for the CLI batch driver;
* ``export_csv()`` emits the reference-compatible results schema
  (io/results.py) for downstream survey tooling.

Simulation ensembles are resumable by PRNG-seed range the same way: key
on the seed + SimParams.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Callable, Iterable, Sequence

import numpy as np


def content_key(source, config=None) -> str:
    """Stable hash of an input + config.

    ``source`` may be a path (hashes file bytes), an ndarray (hashes raw
    bytes + shape), or any reprable object.
    """
    h = hashlib.sha1()
    if isinstance(source, str) and os.path.exists(source):
        with open(source, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
    elif isinstance(source, np.ndarray):
        h.update(str(source.shape).encode())
        h.update(np.ascontiguousarray(source).tobytes())
    else:
        h.update(repr(source).encode())
    if config is not None:
        h.update(repr(config).encode())
    return h.hexdigest()[:16]


class ResultsStore:
    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.json")

    def __contains__(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def put(self, key: str, record: dict) -> None:
        """Atomic write: crash mid-write leaves no half-record behind.
        The tmp name is per-process (like ``put_meta``) so concurrent
        writers of the same key — the serve worker's at-least-once
        duplicate executions — can never interleave bytes through one
        shared tmp file; each write is whole, and the last rename
        wins."""
        tmp = self._path(key) + f".tmp{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(record, fh)
        os.replace(tmp, self._path(key))

    def put_new(self, key: str, record: dict) -> bool:
        """Write-once variant of :meth:`put` for at-least-once
        producers (the serve worker: a lease can expire under a live
        worker, so the same job may execute twice).  A repeat returns
        False and leaves the stored row untouched, so no result is
        ever duplicated.  Two truly concurrent duplicates can both
        pass the existence check, but each write is whole (atomic
        per-process tmp + rename) and duplicate executions of a job
        are deterministic, so the surviving row is valid and identical
        either way."""
        if key in self:
            return False
        self.put(key, record)
        return True

    def get(self, key: str) -> dict | None:
        """A missing OR unreadable/corrupt row degrades to None (as
        ``get_meta`` does): the store is a multi-writer surface under
        the serve protocol, and one bad row must not make
        ``records()``/``export_csv`` raise away every healthy row.

        Degradation is OBSERVABLE, not silent: a corrupt row bumps the
        ``store_corrupt_rows`` counter, logs a ``store_corrupt_row``
        event with the path, and is quarantined aside under a
        ``.corrupt`` suffix — so ``__contains__``/``keys()`` stop
        seeing it (the row re-executes instead of re-parsing the same
        torn bytes on every scan) and the bytes survive for forensics."""
        path = self._path(key)
        try:
            with open(path) as fh:
                return json.load(fh)
        except OSError:
            return None
        except ValueError:
            self._quarantine_corrupt(path)
            return None

    def _quarantine_corrupt(self, path: str) -> None:
        from .. import obs
        from .log import get_logger, log_event

        obs.inc("store_corrupt_rows")
        log_event(get_logger(), "store_corrupt_row", path=path)
        try:
            os.replace(path, path + ".corrupt")
        except OSError:  # fault-ok: already quarantined by a racer
            pass

    def keys(self) -> list[str]:
        return sorted(os.path.splitext(f)[0] for f in os.listdir(self.dir)
                      if f.endswith(".json"))

    def records(self) -> list[dict]:
        return [r for k in self.keys() if (r := self.get(k)) is not None]

    def put_meta(self, name: str, record: dict) -> None:
        """Run metadata (e.g. the resolved auto cuts/scrunch routes),
        kept outside the results namespace: files are ``meta.<name>``
        (no ``.json``), so ``keys()``/``records()``/CSV export never see
        them.  Atomic like ``put``; the tmp name is per-process so two
        CLI runs sharing a store cannot interleave half-writes."""
        path = os.path.join(self.dir, f"meta.{name}")
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(record, fh)
        os.replace(tmp, path)

    def meta_names(self, prefix: str = "") -> list[str]:
        """Names of stored metadata records (optionally filtered by
        prefix) — for record FAMILIES written under per-record keys
        (e.g. ``arc_stack.<digest>``: one atomic file per campaign, so
        concurrent runs can never lose each other's records the way a
        read-modify-append of one shared list would)."""
        return sorted(f[len("meta."):] for f in os.listdir(self.dir)
                      if f.startswith("meta." + prefix)
                      and ".tmp" not in f)

    def get_meta(self, name: str) -> dict | None:
        """Metadata is diagnostic: a missing OR unreadable/corrupt file
        degrades to None rather than failing the run that asked."""
        try:
            with open(os.path.join(self.dir, f"meta.{name}")) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def pending(self, items: Sequence, keyfn: Callable) -> list:
        """Items whose key is not yet in the store (the resume filter)."""
        return [it for it in items if keyfn(it) not in self]

    def export_csv(self, filename: str, full: bool = False) -> int:
        """Write all records to CSV.  Default: the reference-compatible
        schema (io/results.write_results — extra columns like tilt or
        per-arm curvatures are dropped, as the reference's readers
        expect).  ``full=True`` instead writes EVERY column the records
        carry (union of keys, blank where absent) for downstream tools
        that want the beyond-reference measurements.  Returns the row
        count."""
        import csv

        from ..io.results import write_results

        if os.path.exists(filename):
            os.remove(filename)
        rows = [{k: v for k, v in rec.items() if not k.startswith("_")}
                for rec in self.records()]
        if not full:
            # the reference schema REQUIRES name/mjd/... columns; rows
            # without them (e.g. seed-keyed simulation records) cannot
            # be expressed in it and are skipped
            rows = [r for r in rows if "name" in r]
            for row in rows:
                write_results(filename, row)
            return len(rows)
        lead = ["name", "mjd", "freq", "bw", "tobs", "dt", "df"]
        present = {k for r in rows for k in r}
        fields = ([k for k in lead if k in present]
                  + sorted(present - set(lead)))
        with open(filename, "w", newline="") as fh:
            w = csv.DictWriter(fh, fieldnames=fields, restval="")
            w.writeheader()
            w.writerows(rows)
        return len(rows)


def seed_range_pending(store: ResultsStore, seeds: Iterable[int],
                       params) -> list[int]:
    """Resume filter for Monte-Carlo ensembles: seeds without results yet
    (keyed on seed + SimParams)."""
    return [s for s in seeds if content_key(("seed", s), params) not in store]
