"""Resumable results store (SURVEY.md §5 "checkpoint / resume" row).

The reference's closest thing to checkpointing is its append-mode CSV
(scint_utils.py:75-108): a killed batch run can resume because finished
rows are already on disk.  This store makes that pattern explicit and
crash-safe:

* one JSON file per epoch under ``dir/``, keyed by a content hash of the
  input (file bytes or array) + the processing config, written atomically
  (tmp + rename) so partial writes can't corrupt the store;
* ``pending()`` filters a work list down to what is not yet done — the
  resume path for the CLI batch driver;
* ``export_csv()`` emits the reference-compatible results schema
  (io/results.py) for downstream survey tooling.

Simulation ensembles are resumable by PRNG-seed range the same way: key
on the seed + SimParams.

Two write planes (ISSUE 11 — the million-epoch results plane):

* **row files** (``put``/``put_new``): the legacy one-JSON-per-row
  plane — still fully supported, still read by every query;
* **columnar segments** (``put_new_buffered`` + ``flush``; utils/
  segments.py): buffered rows land as ONE append-only checksummed
  segment file per flush, so a campaign of B epochs writes
  O(workers x flushes) files instead of O(B), results are readable the
  moment their flush seals (``row_visibility_s`` histogram measures
  put->visible latency), and the end-of-campaign gather streams the
  segment indexes instead of listdir-ing a million row files.

Every read (``__contains__``/``get``/``keys``/``records``/
``export_csv``/``pending``) merges BOTH planes, so legacy row-file
stores keep draining unchanged and the export bytes are identical
whichever plane wrote the rows.  ``SCINT_RESULTS_PLANE=rows`` forces
the buffered API back onto row files (the A/B baseline the bench lane
and the byte-identity tests compare against).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Callable, Iterable, Sequence

import numpy as np

from . import fsio
from .segments import SegmentStore

SEGMENT_DIRNAME = "segments"
DEFAULT_FLUSH_ROWS = 4096

_LAST_VERSION_STAMP = 0.0


def _version_stamp() -> float:
    """Strictly-increasing version stamps within one process (the
    ``_v`` field ``put_versioned`` rides): two versions of a key
    written inside one clock tick must still resolve newest-wins
    deterministically across the planes."""
    global _LAST_VERSION_STAMP
    t = time.time()
    if t <= _LAST_VERSION_STAMP:
        t = _LAST_VERSION_STAMP + 1e-6
    _LAST_VERSION_STAMP = t
    return round(t, 6)


def _newest_version(*candidates):
    """Cross-plane newest-wins resolution for a duplicated key
    (ROADMAP item 5's open read-policy tail): candidates are
    ``(record | None)`` in DESCENDING legacy priority (row file,
    buffer, segment).  The record with the largest ``_v`` stamp wins;
    records without a stamp (the write-once planes — deterministic
    duplicates by contract) rank below any stamped version, and a tie
    keeps the legacy priority order."""
    best = None
    best_rank = None
    for prio, rec in enumerate(candidates):
        if rec is None:
            continue
        v = rec.get("_v") if isinstance(rec, dict) else None
        rank = (v if isinstance(v, (int, float)) else float("-inf"),
                -prio)
        if best is None or rank > best_rank:
            best, best_rank = rec, rank
    return best


def content_key(source, config=None) -> str:
    """Stable hash of an input + config.

    ``source`` may be a path (hashes file bytes), an ndarray (hashes raw
    bytes + shape), or any reprable object.
    """
    h = hashlib.sha1()
    if isinstance(source, str) and os.path.exists(source):
        with open(source, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
    elif isinstance(source, np.ndarray):
        h.update(str(source.shape).encode())
        h.update(np.ascontiguousarray(source).tobytes())
    else:
        h.update(repr(source).encode())
    if config is not None:
        h.update(repr(config).encode())
    return h.hexdigest()[:16]


class ResultsStore:
    def __init__(self, directory: str, plane: str | None = None,
                 flush_rows: int | None = None):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        plane = plane or os.environ.get("SCINT_RESULTS_PLANE", "segment")
        if plane not in ("segment", "rows"):
            raise ValueError(f"plane={plane!r}: expected 'segment' or "
                             "'rows' (SCINT_RESULTS_PLANE)")
        self.plane = plane
        self.flush_rows = int(flush_rows
                              if flush_rows is not None
                              else os.environ.get(
                                  "SCINT_RESULTS_FLUSH_ROWS",
                                  DEFAULT_FLUSH_ROWS))
        # the segment plane is always READ (legacy stores simply have
        # no segments dir -> one failed stat); plane only selects where
        # the buffered writes go
        self.segments = SegmentStore(os.path.join(directory,
                                                  SEGMENT_DIRNAME))
        # pending buffered rows: key -> (record, buffered_at) — flushed
        # as ONE segment by flush(); insertion order preserved
        self._buf: dict[str, tuple[dict, float]] = {}

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.json")

    def __contains__(self, key: str) -> bool:
        return (os.path.exists(self._path(key))
                or key in self._buf
                or self.segments.has(key))

    def put(self, key: str, record: dict) -> None:
        """Atomic write: crash mid-write leaves no half-record behind.
        The tmp name is per-process (like ``put_meta``) so concurrent
        writers of the same key — the serve worker's at-least-once
        duplicate executions — can never interleave bytes through one
        shared tmp file; each write is whole, and the last rename
        wins."""
        fsio.put_atomic(self._path(key), json.dumps(record))

    def put_new(self, key: str, record: dict) -> bool:
        """Write-once variant of :meth:`put` for at-least-once
        producers (the serve worker: a lease can expire under a live
        worker, so the same job may execute twice).  A repeat returns
        False and leaves the stored row untouched, so no result is
        ever duplicated.  Two truly concurrent duplicates can both
        pass the existence check, but each write is whole (atomic
        per-process tmp + rename) and duplicate executions of a job
        are deterministic, so the surviving row is valid and identical
        either way."""
        if key in self:
            return False
        self.put(key, record)
        return True

    def _row_file_get(self, key: str) -> dict | None:
        """The row-file plane's record for ``key``: missing degrades
        to None, corrupt bytes quarantine aside (observable — see
        :meth:`get`)."""
        path = self._path(key)
        try:
            return json.loads(fsio.read(path))
        except OSError:
            return None
        except ValueError:
            self._quarantine_corrupt(path)
            return None

    def get(self, key: str) -> dict | None:
        """A missing OR unreadable/corrupt row degrades to None (as
        ``get_meta`` does): the store is a multi-writer surface under
        the serve protocol, and one bad row must not make
        ``records()``/``export_csv`` raise away every healthy row.

        Degradation is OBSERVABLE, not silent: a corrupt row bumps the
        ``store_corrupt_rows`` counter, logs a ``store_corrupt_row``
        event with the path, and is quarantined aside under a
        ``.corrupt`` suffix — so ``__contains__``/``keys()`` stop
        seeing it (the row re-executes instead of re-parsing the same
        torn bytes on every scan) and the bytes survive for forensics.

        VERSIONED keys (written by :meth:`put_versioned`, which stamps
        ``_v``) resolve newest-wins ACROSS the planes: a versioned row
        file (a ``plane='rows'`` producer) and versioned segment rows
        compare by stamp instead of the write-once planes' row-file-
        wins rule — so live streaming rows read correctly whichever
        plane the producer ran on.  Unstamped rows keep the legacy
        fast path: a row file satisfies the read without touching the
        segment index."""
        row = self._row_file_get(key)
        if row is not None and "_v" not in row:
            return row            # write-once fast path (legacy rule)
        buffered = self._buf.get(key)
        buf = buffered[0] if buffered is not None else None
        if row is None and buf is not None and "_v" not in buf:
            return buf
        return _newest_version(row, buf, self.segments.get(key))

    def _quarantine_corrupt(self, path: str) -> None:
        from .. import obs
        from .log import get_logger, log_event

        obs.inc("store_corrupt_rows")
        log_event(get_logger(), "store_corrupt_row", path=path)
        try:
            fsio.rename_if_absent(path, path + ".corrupt")
        except OSError:  # fault-ok: already quarantined by a racer
            pass

    # -- the columnar segment plane (ISSUE 11) -----------------------------
    def put_new_buffered(self, key: str, record: dict) -> bool:
        """Write-once buffered put: the row becomes durable (and
        visible to other processes) at the next :meth:`flush`, which
        seals ONE segment file for the whole buffer — the O(flushes)
        replacement for per-row ``put_new`` files.  Dedup semantics
        match ``put_new``: an existing durable OR buffered row returns
        False untouched.  The buffer auto-flushes at ``flush_rows`` so
        a million-epoch campaign holds bounded memory.  Under
        ``plane='rows'`` (SCINT_RESULTS_PLANE=rows — the A/B baseline)
        this degrades to an immediate per-row ``put_new``."""
        if self.plane == "rows":
            return self.put_new(key, record)
        if key in self:
            return False
        self._buf[key] = (record, time.time())
        if len(self._buf) >= self.flush_rows:
            self.flush()
        return True

    def put_versioned(self, key: str, record: dict,
                      series: str | None = None) -> bool:
        """VERSIONED buffered put (ROADMAP item 5 open tail, for item
        2's streaming rows): the newest write under ``key`` WINS at
        read time — ``put_new``'s write-once dedup is deliberately
        bypassed, so a streaming producer can advance a key's value
        tick by tick (live curvature/timescale tracking re-issues the
        same window key per update).

        No format change: the segment plane already reads newest-
        segment-first and dedups by key (``SegmentStore.get`` /
        ``iter_sorted_items`` / ``compact`` all resolve duplicates
        newest-wins), so versioning is a write-policy + read-policy
        change.  Every versioned record is stamped with a strictly-
        increasing ``_v`` (underscore-prefixed: CSV exports never show
        it), which is what lets ``get``/``iter_items`` resolve a
        duplicated key newest-wins even ACROSS planes — a
        ``plane='rows'`` producer's row file and older sealed segment
        versions compare by stamp instead of the write-once planes'
        row-file-wins rule.  A not-yet-flushed buffered version
        supersedes both earlier buffered ones (the buffer is keyed)
        and every sealed one.  Under ``plane='rows'`` this degrades to
        an overwriting (stamped) :meth:`put`.

        ``series`` (optional) tags the record's version GROUP
        (``_series``): the streaming plane stamps each feed's tick
        rows with the stream job id, and ``export_csv(latest_only=
        True)`` keeps only the newest row per series — the final
        values per live feed, instead of the whole tracked time
        series."""
        record = dict(record)
        record["_v"] = _version_stamp()
        if series is not None:
            record["_series"] = str(series)
        if self.plane == "rows":
            self.put(key, record)
            return True
        self._buf[key] = (record, time.time())
        if len(self._buf) >= self.flush_rows:
            self.flush()
        return True

    def flush(self) -> int:
        """Seal the buffered rows as one segment (no-op when empty).
        Returns the number of rows made durable.  Also feeds the
        ``row_visibility_s`` histogram: the put->visible latency of
        each flushed row, the metric that replaces the old plane's
        O(N) end-of-campaign cliff."""
        if not self._buf:
            return 0
        buf, self._buf = self._buf, {}
        try:
            self.segments.append((k, rec)
                                 for k, (rec, _t) in buf.items())
        except BaseException:
            # rows must NEVER vanish after put_new_buffered accepted
            # them: a failed seal (ENOSPC, transient IO) restores the
            # buffer so a caller that survives the exception retries
            # the flush instead of silently losing the batch
            buf.update(self._buf)
            self._buf = buf
            raise
        from .. import obs

        if obs.enabled():
            now = time.time()
            for _rec, t in buf.values():
                obs.observe("row_visibility_s", max(now - t, 0.0))
        return len(buf)

    def compact(self, min_segments: int = 2) -> dict:
        """Merge small segments into one (the serve ``compact`` job
        kind's implementation); flushes pending rows first."""
        self.flush()
        return self.segments.compact(min_segments=min_segments)

    def _row_file_keys(self) -> set[str]:
        try:
            return {os.path.splitext(f)[0]
                    for f in fsio.list(self.dir) if f.endswith(".json")}
        except OSError:
            return set()

    def keys(self) -> list[str]:
        """Every DURABLE key, both planes merged, sorted (buffered
        rows appear after their flush)."""
        return sorted(self._row_file_keys() | self.segments.keys())

    def iter_items(self):
        """Streaming ``(key, record)`` in sorted key order — one
        directory walk + the segment footers, never the whole store in
        memory (the O(N)-memory ``records()`` list was the scale bug
        at exactly the campaign sizes the segment plane targets).
        Row files win over segments for a duplicated key of the
        write-once planes (both are deterministic duplicates under the
        at-least-once contract); VERSIONED duplicates (``_v``-stamped
        — put_versioned) resolve newest-wins across the planes, same
        rule as :meth:`get`."""
        row_keys = self._row_file_keys()
        if not self.segments.keys():
            for k in sorted(row_keys):
                rec = self._row_file_get(k)
                if rec is not None:
                    yield k, rec
            return
        seg_items = self.segments.iter_sorted_items()
        seg_next = next(seg_items, None)
        for k in sorted(row_keys):
            while seg_next is not None and seg_next[0] < k:
                yield seg_next
                seg_next = next(seg_items, None)
            seg_rec = None
            if seg_next is not None and seg_next[0] == k:
                seg_rec = seg_next[1]
                seg_next = next(seg_items, None)
            rec = self._row_file_get(k)
            if rec is not None and seg_rec is not None \
                    and ("_v" in rec or "_v" in seg_rec):
                rec = _newest_version(rec, None, seg_rec)
            if rec is not None:
                yield k, rec
            elif seg_rec is not None:
                yield k, seg_rec
        while seg_next is not None:
            yield seg_next
            seg_next = next(seg_items, None)

    def records(self):
        """Streaming generator over all records in key order (was a
        fully-materialised list — O(N) memory per call)."""
        return (rec for _k, rec in self.iter_items())

    def put_meta(self, name: str, record: dict) -> None:
        """Run metadata (e.g. the resolved auto cuts/scrunch routes),
        kept outside the results namespace: files are ``meta.<name>``
        (no ``.json``), so ``keys()``/``records()``/CSV export never see
        them.  Atomic like ``put``; the tmp name is per-process so two
        CLI runs sharing a store cannot interleave half-writes."""
        fsio.put_atomic(os.path.join(self.dir, f"meta.{name}"),
                        json.dumps(record))

    def meta_names(self, prefix: str = "") -> list[str]:
        """Names of stored metadata records (optionally filtered by
        prefix) — for record FAMILIES written under per-record keys
        (e.g. ``arc_stack.<digest>``: one atomic file per campaign, so
        concurrent runs can never lose each other's records the way a
        read-modify-append of one shared list would)."""
        return sorted(f[len("meta."):] for f in fsio.list(self.dir)
                      if f.startswith("meta." + prefix)
                      and ".tmp" not in f)

    def get_meta(self, name: str) -> dict | None:
        """Metadata is diagnostic: a missing OR unreadable/corrupt file
        degrades to None rather than failing the run that asked."""
        try:
            return json.loads(fsio.read(
                os.path.join(self.dir, f"meta.{name}")))
        except (OSError, ValueError):
            return None

    def pending(self, items: Sequence, keyfn: Callable) -> list:
        """Items whose key is not yet in the store (the resume filter)."""
        return [it for it in items if keyfn(it) not in self]

    def _latest_only_keys(self) -> set[str]:
        """One key per version SERIES (``_series``-tagged records —
        put_versioned): the newest-``_v`` row of each series, ties
        broken by key order.  The ``--latest-only`` export filter's
        keep-set; untagged records are never filtered."""
        best: dict[str, tuple] = {}
        for k, rec in self.iter_items():
            s = rec.get("_series")
            if s is None:
                continue
            v = rec.get("_v")
            rank = (v if isinstance(v, (int, float)) else float("-inf"),
                    k)
            if s not in best or rank > best[s][0]:
                best[s] = (rank, k)
        return {k for _rank, k in best.values()}

    def _export_items(self, latest_only: bool):
        """The export row stream: all durable records, optionally with
        each version series collapsed to its newest row."""
        if not latest_only:
            yield from self.iter_items()
            return
        keep = self._latest_only_keys()
        for k, rec in self.iter_items():
            if rec.get("_series") is not None and k not in keep:
                continue
            yield k, rec

    def export_csv(self, filename: str, full: bool = False,
                   latest_only: bool = False) -> int:
        """Write all records to CSV.  Default: the reference-compatible
        schema (io/results.results_line — extra columns like tilt or
        per-arm curvatures are dropped, as the reference's readers
        expect).  ``full=True`` instead writes EVERY column the records
        carry (union of keys, blank where absent) for downstream tools
        that want the beyond-reference measurements.  Returns the row
        count.

        ``latest_only=True`` collapses each VERSION SERIES (records a
        streaming producer tagged via ``put_versioned(series=...)``)
        to its newest row — the final value per live feed instead of
        the whole tracked time series; untagged records always export.
        Internal underscore columns (``_v``/``_series``) never appear
        in either schema.

        STREAMS both planes (rows are read once for the reference
        schema, twice for ``full`` — fieldname-union pass then the
        write pass) with one open output handle, instead of the old
        materialise-everything-then-reopen-per-row gather whose cost
        was O(N) memory plus O(N) file opens at campaign end.  Output
        bytes are identical to the old path (same key order, same
        formatter) whichever plane wrote the rows."""
        import csv

        from ..io.results import results_line

        if os.path.exists(filename):
            os.remove(filename)
        if not full:
            # the reference schema REQUIRES name/mjd/... columns; rows
            # without them (e.g. seed-keyed simulation records) cannot
            # be expressed in it and are skipped.  File created lazily
            # on the first row, matching the appender's behaviour
            # (zero rows -> no file).
            n = 0
            out = None
            try:
                for _key, rec in self._export_items(latest_only):
                    row = {k: v for k, v in rec.items()
                           if not k.startswith("_")}
                    if "name" not in row:
                        continue
                    header, line = results_line(row)
                    if out is None:
                        out = open(filename, "w")
                        out.write(header + "\n")
                    out.write(line + "\n")
                    n += 1
            finally:
                if out is not None:
                    out.close()
            return n
        lead = ["name", "mjd", "freq", "bw", "tobs", "dt", "df"]
        present = {k for _key, rec in self._export_items(latest_only)
                   for k in rec if not k.startswith("_")}
        fields = ([k for k in lead if k in present]
                  + sorted(present - set(lead)))
        n = 0
        with open(filename, "w", newline="") as fh:
            w = csv.DictWriter(fh, fieldnames=fields, restval="")
            w.writeheader()
            for _key, rec in self._export_items(latest_only):
                w.writerow({k: v for k, v in rec.items()
                            if not k.startswith("_")})
                n += 1
        return n


def seed_range_pending(store: ResultsStore, seeds: Iterable[int],
                       params) -> list[int]:
    """Resume filter for Monte-Carlo ensembles: seeds without results yet
    (keyed on seed + SimParams)."""
    return [s for s in seeds if content_key(("seed", s), params) not in store]
