"""The storage driver seam under every durable plane (ISSUE 20).

Seven on-disk planes (lane x shard queue records + leases, segment
results, stream feed chunks + manifests, resume cursors, heartbeats,
controller hints/pool status, drain markers) each grew the same
hand-rolled durability idiom: write ``<path>.tmp<pid>`` then
``os.replace``, arbitrate ownership with ``os.rename``, salvage torn
tails on read.  This module names that idiom as a SMALL verb set —

* :func:`put_atomic`   — whole-file publish (tmp + optional fsync +
  atomic replace); readers never observe a torn file
* :func:`append`       — ordered bytes through a held handle (the
  segment appender's block log; torn tails are the READER's contract)
* :func:`read`         — whole-file fetch (``FileNotFoundError`` and
  other ``OSError`` propagate unchanged: callers already classify)
* :func:`list`         — directory listing
* :func:`delete`       — idempotent-at-the-caller unlink
* :func:`rename_if_absent` — ownership arbitration: exactly one of N
  racing callers wins, the losers see the source vanish (``OSError``).
  The local driver is plain ``os.rename`` — POSIX rename overwrites,
  so *callers keep their own existence probe* where dst collisions are
  possible (the queue's claim path renames to a per-job leased name,
  where the source-vanish race IS the arbitration).  An object-store
  driver maps this verb to a conditional PUT (if-none-match) — the
  ROADMAP item 3 port — which is why the verb carries the stricter
  name.

— and routes every plane through it, byte-identical formats, so ONE
driver underlies the whole fleet directory.  ``DRIVER`` is the
module-level default (:class:`LocalDriver`); a future object-store
backend replaces it wholesale and must satisfy the invariant catalog
in docs/reliability.md.

Crash-point enumeration
-----------------------

Every *mutating* verb call (put/append/delete/rename) is one crash
point.  Two instrumentation modes, both OFF by default (the disarmed
cost is one dict lookup via :func:`faults.check` plus one module-
global ``is None`` test — no syscalls, no env reads):

1. **Per-site fault specs** — the ``fsio.*`` sites accept the storage
   fault kinds (``torn_write`` / ``crash_before_rename`` /
   ``crash_after_rename`` / ``enospc`` / ``eio``) via
   ``faults.inject`` or ``SCINT_FAULTS``.  The errno kinds raise an
   ``OSError`` the caller's existing handlers classify; the crash
   kinds perform the spec'd partial work then hard-exit
   (``os._exit``) — indistinguishable from SIGKILL at that boundary.
2. **The global sweep** (the chaos harness) — env-driven so a
   subprocess stub can walk a full lifecycle:

   * ``SCINT_FSIO_COUNT_FILE=<path>``: count mutating verb calls and
     write the total to ``<path>`` at interpreter exit (the clean run
     learns K = the number of crash points to sweep).
   * ``SCINT_FSIO_CRASH_POINT=<k>`` + ``SCINT_FSIO_CRASH_KIND=torn|
     before|after``: hard-kill the process AT the k-th (1-based)
     mutating verb call — ``torn`` leaves partial bytes, ``before``
     completes none of the op, ``after`` completes all of it.
     Sweeping ``torn`` and ``after`` at every k covers every crash
     boundary, because all durable mutations route through here.

:func:`crash_points` exposes the running count for in-process
harnesses.  See docs/reliability.md for the invariant catalog each
crash point must recover into.
"""

from __future__ import annotations

import os

from .. import faults

ENV_COUNT_FILE = "SCINT_FSIO_COUNT_FILE"
ENV_CRASH_POINT = "SCINT_FSIO_CRASH_POINT"
ENV_CRASH_KIND = "SCINT_FSIO_CRASH_KIND"
ENV_FSYNC = "SCINT_FSIO_FSYNC"

# exit code of an injected hard kill (distinct from real SIGKILL's
# -9 so the harness can assert the crash actually came from the
# enumerated point, not an unrelated abort)
CRASH_EXIT_CODE = 86

_CRASHES = ("torn", "before", "after")


class LocalDriver:
    """The default backend: local POSIX filesystem, the exact syscall
    sequences the planes used before the seam (no extra syscalls; the
    acceptance criterion).  ``fsync`` is opt-in per call or globally
    via ``SCINT_FSIO_FSYNC=1`` — the planes' recovery paths are
    rename-ordering-based, not fsync-based, and the disarmed hot path
    must not grow a syscall."""

    def __init__(self):
        self.fsync_default = bool(os.environ.get(ENV_FSYNC, ""))

    # -- verbs --------------------------------------------------------------
    def put_atomic(self, path: str, data: bytes, *,
                   fsync: bool = False, crash: str | None = None) -> None:
        tmp = f"{path}.tmp{os.getpid()}"
        if crash == "torn":
            with open(tmp, "wb") as fh:
                fh.write(data[: len(data) // 2])
            hard_exit()
        with open(tmp, "wb") as fh:
            fh.write(data)
            if fsync or self.fsync_default:
                fh.flush()
                os.fsync(fh.fileno())
        if crash == "before":
            hard_exit()
        os.replace(tmp, path)
        if crash == "after":
            hard_exit()

    def append(self, fh, data: bytes, *, crash: str | None = None) -> None:
        if crash == "torn":
            fh.write(data[: len(data) // 2])
            fh.flush()
            hard_exit()
        if crash == "before":
            hard_exit()
        fh.write(data)
        if crash == "after":
            fh.flush()
            hard_exit()

    def read(self, path: str) -> bytes:
        with open(path, "rb") as fh:
            return fh.read()

    def list(self, path: str) -> list[str]:
        return os.listdir(path)

    def delete(self, path: str, *, crash: str | None = None) -> None:
        if crash in ("torn", "before"):
            hard_exit()
        if crash == "after":
            # "after" = killed the instant the syscall returns control,
            # whatever its outcome — an ENOENT probe is still a crash
            # boundary (callers probe several candidate paths)
            try:
                os.remove(path)
            finally:
                hard_exit()
        os.remove(path)

    def rename_if_absent(self, src: str, dst: str, *,
                         crash: str | None = None) -> None:
        if crash in ("torn", "before"):
            hard_exit()
        if crash == "after":
            try:
                os.rename(src, dst)
            finally:
                hard_exit()
        os.rename(src, dst)


DRIVER = LocalDriver()


# ---------------------------------------------------------------------------
# crash-point instrumentation
# ---------------------------------------------------------------------------

def hard_exit() -> None:
    """Die NOW: no atexit, no buffered flushes, no finally blocks —
    the same boundary a SIGKILL leaves."""
    os._exit(CRASH_EXIT_CODE)


class _Sweep:
    """The env-driven global crash-point mode (see module doc)."""

    def __init__(self, count_file: str | None, crash_point: int,
                 crash_kind: str):
        if crash_kind not in _CRASHES:
            raise ValueError(
                f"{ENV_CRASH_KIND}: unknown kind {crash_kind!r} "
                f"(expected one of {'/'.join(_CRASHES)})")
        self.count_file = count_file
        self.crash_point = crash_point
        self.crash_kind = crash_kind
        self.mutations = 0
        if count_file:
            import atexit
            atexit.register(self._write_count)

    def _write_count(self) -> None:
        try:
            with open(self.count_file, "w") as fh:
                fh.write(str(self.mutations))
        except OSError:  # fault-ok: harness bookkeeping only
            pass

    def step(self) -> str | None:
        self.mutations += 1
        if self.crash_point and self.mutations == self.crash_point:
            return self.crash_kind
        return None


def _sweep_from_env():
    count_file = os.environ.get(ENV_COUNT_FILE) or None
    point = int(os.environ.get(ENV_CRASH_POINT, "0") or "0")
    if count_file is None and point <= 0:
        return None
    return _Sweep(count_file, point,
                  os.environ.get(ENV_CRASH_KIND) or "torn")


_SWEEP = _sweep_from_env()


def arm(crash_point: int = 0, crash_kind: str = "torn",
        count_file: str | None = None) -> None:
    """(Re)arm the sweep in-process — the fork-based chaos harness arms
    each forked child without env vars or a reimport.  With no args,
    disarms."""
    global _SWEEP
    _SWEEP = (_Sweep(count_file, crash_point, crash_kind)
              if (count_file or crash_point > 0) else None)


def crash_points() -> int:
    """Mutating verb calls so far in this process (0 when the sweep
    instrumentation is off — counting costs nothing disarmed)."""
    return _SWEEP.mutations if _SWEEP is not None else 0


def _gate(verb: str, mutating: bool = True) -> str | None:
    """The per-verb instrumentation gate.  Disarmed: one dict lookup
    (``faults.check``) + one ``is None`` test.  Returns a crash
    directive (``torn``/``before``/``after``) for the verb to
    choreograph, or ``None``; errno fault kinds raise here."""
    try:
        faults.check(f"fsio.{verb}")
    except faults.InjectedCrash as e:
        return e.crash
    if _SWEEP is not None and mutating:
        return _SWEEP.step()
    return None


# ---------------------------------------------------------------------------
# the plane-facing API (module functions so every backend shares the
# fault/crash seam; swap backends by assigning ``fsio.DRIVER``)
# ---------------------------------------------------------------------------

def put_atomic(path: str, data: bytes | str, *,
               fsync: bool = False) -> None:
    """Atomically publish ``data`` as ``path`` (tmp + replace; fsync
    opt-in).  ``str`` data is UTF-8 encoded.  Readers never observe a
    torn file; a crash leaves either the old content or the new, plus
    at most one orphaned ``.tmp`` (the fsck catalog's O1)."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    DRIVER.put_atomic(path, data, fsync=fsync, crash=_gate("put"))


def append(fh, data: bytes) -> None:
    """Ordered bytes through a held handle (the segment block log).
    Torn tails are the reader's contract (``scan_blocks`` salvage)."""
    DRIVER.append(fh, data, crash=_gate("append"))


def read(path: str) -> bytes:
    """Whole-file fetch.  ``FileNotFoundError``/``OSError`` propagate
    unchanged — callers already classify (vanished = re-sync, torn =
    quarantine)."""
    _gate("read", mutating=False)
    return DRIVER.read(path)


def list(path: str) -> list[str]:  # noqa: A001 - the verb set's name
    """Directory listing.  ``OSError`` propagates — a vanished
    directory is a re-sync signal, never corruption."""
    _gate("list", mutating=False)
    return DRIVER.list(path)


def delete(path: str) -> None:
    """Unlink.  ``OSError`` propagates; callers treat a vanished path
    as already-deleted (idempotent at the call site)."""
    DRIVER.delete(path, crash=_gate("delete"))


def rename_if_absent(src: str, dst: str) -> None:
    """Ownership arbitration: move ``src`` to ``dst``; of N racing
    callers exactly one wins and the rest see the source vanish
    (``OSError``).  Local driver: plain ``os.rename`` (see module doc
    for the conditional-PUT mapping and the caller-side existence
    probe contract)."""
    DRIVER.rename_if_absent(src, dst, crash=_gate("rename"))
