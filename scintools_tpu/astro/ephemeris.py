"""Analytic solar-system ephemeris: Earth barycentric position & velocity.

The reference computes Earth's barycentric state with astropy
(``get_earth_velocity`` / ``get_ssb_delay``, scint_utils.py:160-194,
134-157).  astropy is not a dependency of this framework, so this module
implements a self-contained analytic ephemeris:

* Keplerian mean elements of the Earth-Moon barycenter and the four giant
  planets (Standish's approximate elements, valid 1800-2050 AD, J2000
  ecliptic frame), propagated with a fixed-iteration Newton Kepler solver
  (jit/vmap-safe: no data-dependent control flow).
* The Sun's offset from the solar-system barycenter is recovered from the
  giant-planet positions (point-mass barycenter), so positions are
  *barycentric*, not heliocentric.

Accuracy (vs JPL ephemerides, dominated by the neglected Earth-Moon
separation of ~4700 km and perturbations of the inner planets):
position ~1e-4 AU (=> Romer-delay error <~0.1 s out of +-500 s), velocity
~0.02 km/s (Earth's orbital speed is ~30 km/s; Earth's motion about the
EMB contributes ~0.012 km/s).  These bounds are a REGRESSION TEST, not a
claim: tests/test_astro.py pins this module against a committed golden
table (tests/data/earth_ephemeris_golden.json) generated from an
independent truncated-VSOP87D + IAU-precession truth source
(tests/vsop87_truth.py; measured headroom ~7e-5 AU / ~0.014 km/s over
1990-2040).  This is far below the km/s-scale effective
velocities the scintillation models fit (models/velocity.py), and well
below typical vism uncertainties.

All functions take MJD (TT ~ TDB to <2 ms) scalars or arrays and work on
numpy by default; pass ``xp=jax.numpy`` for traced/jitted evaluation.
"""

from __future__ import annotations

import numpy as np

AU_KM = 1.495978707e8          # km
AU_M = 1.495978707e11          # m
C_M_S = 299792458.0            # m/s
DAY_S = 86400.0
_OBLIQUITY_J2000 = np.deg2rad(23.439291111)

# Standish approximate Keplerian elements, 1800-2050 AD (public JPL tables):
# a [AU], e, I [deg], L [deg], long.peri [deg], Omega [deg]; value + rate
# per Julian century from J2000.
_ELEMENTS = {
    "emb": ((1.00000261, 0.00000562), (0.01671123, -0.00004392),
            (-0.00001531, -0.01294668), (100.46457166, 35999.37244981),
            (102.93768193, 0.32327364), (0.0, 0.0)),
    "jupiter": ((5.20288700, -0.00011607), (0.04838624, -0.00013253),
                (1.30439695, -0.00183714), (34.39644051, 3034.74612775),
                (14.72847983, 0.21252668), (100.47390909, 0.20469106)),
    "saturn": ((9.53667594, -0.00125060), (0.05386179, -0.00050991),
               (2.48599187, 0.00193609), (49.95424423, 1222.49362201),
               (92.59887831, -0.41897216), (113.66242448, -0.28867794)),
    "uranus": ((19.18916464, -0.00196176), (0.04725744, -0.00004397),
               (0.77263783, -0.00242939), (313.23810451, 428.48202785),
               (170.95427630, 0.40805281), (74.01692503, 0.04240589)),
    "neptune": ((30.06992276, 0.00026291), (0.00859048, 0.00005105),
                (1.77004347, 0.00035372), (-55.12002969, 218.45945325),
                (44.96476227, -0.32241464), (131.78422574, -0.00508664)),
}

# planet/Sun mass ratios (IAU nominal values)
_MASS_RATIO = {"jupiter": 9.5479194e-4, "saturn": 2.8588567e-4,
               "uranus": 4.3662440e-5, "neptune": 5.1513890e-5}


def solve_kepler(M, e, xp=np, iters: int = 15):
    """Eccentric anomaly E from mean anomaly M (radians): fixed-iteration
    Newton, jit/vmap-safe (converges to machine precision for e < 0.95 in
    well under 15 iterations)."""
    E = M + e * xp.sin(M)
    for _ in range(iters):
        E = E - (E - e * xp.sin(E) - M) / (1.0 - e * xp.cos(E))
    return E


def _body_posvel_ecliptic(body: str, mjd, xp=np):
    """Heliocentric position [AU] and velocity [AU/day] in the J2000
    ecliptic frame from the mean elements.  mjd may be an array."""
    mjd = xp.asarray(mjd, dtype=np.float64)
    T = (mjd - 51544.5) / 36525.0  # Julian centuries from J2000.0
    (a0, ad), (e0, ed), (i0, idot), (L0, Ld), (w0, wd), (O0, Od) = \
        _ELEMENTS[body]
    a = a0 + ad * T
    e = e0 + ed * T
    inc = xp.deg2rad(i0 + idot * T)
    L = xp.deg2rad(L0 + Ld * T)
    lperi = xp.deg2rad(w0 + wd * T)
    Omega = xp.deg2rad(O0 + Od * T)
    omega = lperi - Omega
    M = xp.mod(L - lperi + np.pi, 2 * np.pi) - np.pi

    E = solve_kepler(M, e, xp=xp)
    cosE, sinE = xp.cos(E), xp.sin(E)
    b_over_a = xp.sqrt(1.0 - e ** 2)
    xo = a * (cosE - e)
    yo = a * b_over_a * sinE

    # d/dt: mean motion from the L rate (rad/day); element rates are
    # negligible over one sample (they matter only through M above)
    n = xp.deg2rad(Ld - wd) / 36525.0
    Edot = n / (1.0 - e * cosE)
    vxo = -a * sinE * Edot
    vyo = a * b_over_a * cosE * Edot

    co, so = xp.cos(omega), xp.sin(omega)
    cO, sO = xp.cos(Omega), xp.sin(Omega)
    ci, si = xp.cos(inc), xp.sin(inc)
    r11 = co * cO - so * sO * ci
    r12 = -so * cO - co * sO * ci
    r21 = co * sO + so * cO * ci
    r22 = -so * sO + co * cO * ci
    r31 = so * si
    r32 = co * si

    def rot(px, py):
        return (r11 * px + r12 * py, r21 * px + r22 * py, r31 * px + r32 * py)

    return rot(xo, yo), rot(vxo, vyo)


def _ecliptic_to_equatorial(vec3, xp=np):
    x, y, z = vec3
    ce, se = np.cos(_OBLIQUITY_J2000), np.sin(_OBLIQUITY_J2000)
    return x, ce * y - se * z, se * y + ce * z


def earth_posvel(mjd, xp=np):
    """Earth barycentric (SSB) position [AU] and velocity [AU/day] in the
    J2000 *equatorial* frame, as two (x, y, z) tuples of arrays.

    Earth is approximated by the Earth-Moon barycenter; the Sun's offset
    from the SSB is reconstructed from the four giant planets.
    """
    (ex, ey, ez), (evx, evy, evz) = _body_posvel_ecliptic("emb", mjd, xp=xp)
    # Sun wrt SSB = -sum(m_p/M_tot * r_p heliocentric)
    mtot = 1.0 + sum(_MASS_RATIO.values())
    sx = sy = sz = svx = svy = svz = 0.0
    for body, mu in _MASS_RATIO.items():
        (px, py, pz), (pvx, pvy, pvz) = _body_posvel_ecliptic(body, mjd,
                                                              xp=xp)
        f = mu / mtot
        sx, sy, sz = sx - f * px, sy - f * py, sz - f * pz
        svx, svy, svz = svx - f * pvx, svy - f * pvy, svz - f * pvz
    pos = _ecliptic_to_equatorial((ex + sx, ey + sy, ez + sz), xp=xp)
    vel = _ecliptic_to_equatorial((evx + svx, evy + svy, evz + svz), xp=xp)
    return pos, vel


def _radec_basis(raj: float, decj: float, xp=np):
    """Unit vectors: line of sight n, +RA (east) and +DEC (north) tangent
    directions, in the J2000 equatorial frame.  raj/decj in radians."""
    cr, sr = xp.cos(raj), xp.sin(raj)
    cd, sd = xp.cos(decj), xp.sin(decj)
    n = (cd * cr, cd * sr, sd)
    e_ra = (-sr, cr, 0.0)
    e_dec = (-cr * sd, -sr * sd, cd)
    return n, e_ra, e_dec


def get_earth_velocity(mjds, raj: float, decj: float, xp=np):
    """Earth's barycentric velocity projected on the +RA / +DEC sky
    directions of a source, in km/s (reference: scint_utils.py:160-194,
    which uses astropy ``get_body_barycentric_posvel``).

    Parameters: mjds array, raj/decj in radians.
    Returns (vearth_ra, vearth_dec) arrays in km/s.
    """
    _, (vx, vy, vz) = earth_posvel(mjds, xp=xp)
    _, e_ra, e_dec = _radec_basis(raj, decj, xp=xp)
    to_kms = AU_KM / DAY_S
    v_ra = (vx * e_ra[0] + vy * e_ra[1] + vz * e_ra[2]) * to_kms
    v_dec = (vx * e_dec[0] + vy * e_dec[1] + vz * e_dec[2]) * to_kms
    return v_ra, v_dec


def get_ssb_delay(mjds, raj: float, decj: float, xp=np):
    """Romer delay (s) from the geocenter to the solar-system barycenter
    for a source at (raj, decj) radians (reference: scint_utils.py:134-157).

    Positive when Earth is on the source side of the SSB: barycentric
    arrival time = topocentric MJD + delay/86400.
    """
    (x, y, z), _ = earth_posvel(mjds, xp=xp)
    n, _, _ = _radec_basis(raj, decj, xp=xp)
    return (x * n[0] + y * n[1] + z * n[2]) * AU_M / C_M_S


def get_true_anomaly(mjds, pars: dict, xp=np):
    """True anomaly of the pulsar orbit at each MJD (reference:
    scint_utils.py:281-314, which fsolves Kepler per epoch; here a
    fixed-iteration Newton solve, vmap-safe).

    ``pars`` needs T0 [MJD], PB [days], ECC; optional PBDOT (s/s, as in
    tempo2 par files — the reference applies the same 1e-12 heuristic for
    values given in 1e-12 s/s units, replicated here).
    """
    mjds = xp.asarray(mjds, dtype=np.float64)
    T0, PB = pars["T0"], pars["PB"]
    ECC = pars.get("ECC", 0.0)
    PBDOT = pars.get("PBDOT", 0.0)
    if abs(PBDOT) > 1e-6:  # given in units of 1e-12 s/s
        PBDOT = PBDOT * 1e-12
    nb = 2 * np.pi / PB  # rad/day

    tsince = mjds - T0
    # mean anomaly with linear period derivative (d(PB)/dt = PBDOT)
    M = nb * (tsince - 0.5 * (PBDOT / PB) * tsince ** 2)
    M = xp.mod(M, 2 * np.pi)
    E = solve_kepler(M, ECC, xp=xp)
    return 2 * xp.arctan2(xp.sqrt(1 + ECC) * xp.sin(E / 2),
                          xp.sqrt(1 - ECC) * xp.cos(E / 2))
