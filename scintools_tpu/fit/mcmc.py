"""jax-native affine-invariant ensemble MCMC (Goodman & Weare 2010).

The reference's posterior option is ``lmfit.Minimizer.emcee`` + corner
plots inside ``get_scint_params(mcmc=True)`` (dynspec.py:989-992,
1025-1031).  Neither lmfit nor emcee is a dependency here, so this module
implements the same stretch-move ensemble algorithm natively in JAX:
every walker move is a fixed-shape ``lax.scan`` step with the walker
batch vmapped, so a whole posterior sampling run is ONE jit-compiled
device program (and itself vmappable over epochs).

The parallel stretch move: walkers are split into two halves; each half
proposes along lines through partners drawn from the *other* half with
scale ``z ~ g(z) ∝ 1/sqrt(z)`` on [1/a, a], accepted with probability
``z^(ndim-1) L(prop)/L(cur)`` — which preserves detailed balance and is
affine-invariant (no tuning to parameter scales/correlations).
"""

from __future__ import annotations

import functools

import numpy as np

from ..data import ScintParams


def _build_sampler(ndim: int, nwalkers: int, steps: int, a: float,
                   log_prob_fn):
    """jit'd sampler for ``log_prob_fn(p, *data_args) -> scalar``.

    Data flows through as traced arguments (NOT captured in the closure),
    so factories that cache the result stay cacheable on static shapes.
    """
    import jax
    import jax.numpy as jnp

    half = nwalkers // 2

    def update_half(key, group, other, lp_group, data):
        kz, ki, ka = jax.random.split(key, 3)
        z = ((a - 1.0) * jax.random.uniform(kz, (half,)) + 1.0) ** 2 / a
        idx = jax.random.randint(ki, (half,), 0, half)
        prop = other[idx] + z[:, None] * (group - other[idx])
        lp_prop = jax.vmap(lambda p: log_prob_fn(p, *data))(prop)
        log_ratio = (ndim - 1) * jnp.log(z) + lp_prop - lp_group
        accept = jnp.log(jax.random.uniform(ka, (half,))) < log_ratio
        return (jnp.where(accept[:, None], prop, group),
                jnp.where(accept, lp_prop, lp_group))

    @jax.jit
    def run(key, p0, *data):
        lp0 = jax.vmap(lambda p: log_prob_fn(p, *data))(p0)

        def one_step(carry, key):
            w, lp = carry
            k1, k2 = jax.random.split(key)
            g1, g2 = w[:half], w[half:]
            l1, l2 = lp[:half], lp[half:]
            g1, l1 = update_half(k1, g1, g2, l1, data)
            g2, l2 = update_half(k2, g2, g1, l2, data)
            w = jnp.concatenate([g1, g2])
            lp = jnp.concatenate([l1, l2])
            return (w, lp), (w, lp)

        keys = jax.random.split(key, steps)
        _, (chain, lps) = jax.lax.scan(one_step, (p0, lp0), keys)
        return chain, lps

    return run


def ensemble_sample(log_prob_fn, p0, key=None, steps: int = 500,
                    a: float = 2.0, data_args: tuple = ()):
    """Sample ``log_prob_fn`` with the stretch-move ensemble.

    p0: [nwalkers, ndim] initial walkers (nwalkers even, >= 2*ndim
    recommended).  Returns (chain [steps, nwalkers, ndim],
    log_probs [steps, nwalkers]) as device arrays.

    ``log_prob_fn(p, *data_args)`` must be jax-traceable over a [ndim]
    vector, returning a scalar (``-jnp.inf`` outside the prior support).
    Each call builds and jit-compiles a fresh sampler; for repeated
    sampling over many datasets of the same shape, build once via the
    cached factories (see :func:`fit_scint_params_mcmc`) or close over
    ``jax.jit`` yourself.
    """
    import jax

    p0 = np.asarray(p0)
    if p0.ndim != 2 or p0.shape[0] % 2:
        raise ValueError("p0 must be [nwalkers(even), ndim]")
    if key is None:
        key = jax.random.PRNGKey(0)
    run = _build_sampler(p0.shape[1], p0.shape[0], int(steps), float(a),
                         log_prob_fn)
    return run(key, p0, *data_args)


def _posterior_summary(chain, burn, ndim):
    """Post-burn medians and stds, chain flattened over walkers."""
    post = np.asarray(chain[burn:]).reshape(-1, ndim)
    return np.median(post, axis=0), np.std(post, axis=0), post


@functools.lru_cache(maxsize=32)
def _scint2d_sampler_cached(crop_t: int, crop_f: int,
                            alpha: float | None, nwalkers: int,
                            steps: int):
    """Sampler for the 2-D ACF posterior (tau, dnu, amp, wn, tilt
    [, alpha]), cached on static shapes; the window, lag grids, taper
    scales and noise scale are traced arguments."""
    import jax.numpy as jnp

    from ..models.acf_models import scint_acf_model_2d

    free = alpha is None

    def log_prob(p, win, x_t, x_f, tmax, fmax, sigma):
        tau, dnu, amp, wn, tilt = p[0], p[1], p[2], p[3], p[4]
        a_ = p[5] if free else alpha
        inside = (tau > 0) & (dnu > 0) & (amp > 0) & (wn >= 0)
        if free:
            inside = inside & (a_ > 0) & (a_ < 8.0)
        m = scint_acf_model_2d(x_t, x_f, tau, dnu, amp, wn, a_, tilt,
                               tmax=tmax, fmax=fmax, xp=jnp)
        chi2 = jnp.sum(((win - m) / sigma) ** 2)
        return jnp.where(inside, -0.5 * chi2, -jnp.inf)

    return _build_sampler(6 if free else 5, nwalkers, steps, 2.0,
                          log_prob)


def fit_scint_params_2d_mcmc(acf2d, dt, df, nchan: int, nsub: int,
                             alpha: float | None = 5 / 3,
                             crop_frac: float = 0.5, nwalkers: int = 32,
                             steps: int = 600, burn: int = 300,
                             seed: int = 0, return_chain: bool = False):
    """Posterior over the 2-D ACF model incl. phase-gradient tilt — the
    ``mcmc=True`` analogue of :func:`fit.scint_fit.fit_scint_params_2d`
    (reference surface: get_scint_params mcmc, dynspec.py:989-992,
    extended to the acf2d method it never finished).

    Returns (ScintParams, tilt, tilterr) with posterior medians/stds
    (plus the post-burn chain when ``return_chain``: columns
    tau, dnu, amp, wn, tilt[, alpha]).
    """
    import jax
    import jax.numpy as jnp

    from ..models.acf_models import scint_acf_model_2d
    from .scint_fit import (_crop_acf_2d, acf2d_crop_sizes, acf_lags_2d,
                            fit_scint_params_2d)

    if burn >= steps:
        raise ValueError(f"burn ({burn}) must be < steps ({steps})")
    free = alpha is None
    lm_sp, lm_tilt, _ = fit_scint_params_2d(acf2d, dt, df, nchan, nsub,
                                            alpha=alpha, backend="numpy",
                                            crop_frac=crop_frac)
    alpha_best = float(np.asarray(lm_sp.talpha))
    p_best = np.array([float(lm_sp.tau), float(lm_sp.dnu),
                       float(lm_sp.amp), float(lm_sp.wn), float(lm_tilt)]
                      + ([alpha_best] if free else []))
    ndim = len(p_best)
    a = np.asarray(acf2d, dtype=np.float64)
    crop_t, crop_f = acf2d_crop_sizes(nchan, nsub, crop_frac)
    win = _crop_acf_2d(a, nchan, nsub, crop_t, crop_f)
    x_t, x_f = acf_lags_2d(float(dt), float(abs(df)), crop_t, crop_f,
                           xp=np)
    tmax, fmax = float(dt) * nsub, float(abs(df)) * nchan
    resid = win - scint_acf_model_2d(
        x_t, x_f, p_best[0], p_best[1], p_best[2], p_best[3],
        alpha_best, p_best[4], tmax=tmax, fmax=fmax, xp=np)
    sigma = max(float(np.std(resid)), 1e-12)

    rng = np.random.default_rng(seed)
    p0 = p_best * (1.0 + 0.01 * rng.standard_normal((nwalkers, ndim)))
    # keep positivity-constrained dims inside the prior; tilt may be 0
    # or negative, so jitter it additively instead
    p0[:, :4] = np.abs(p0[:, :4]) + 1e-12
    p0[:, 4] = p_best[4] + 0.01 * rng.standard_normal(nwalkers)
    run = _scint2d_sampler_cached(crop_t, crop_f,
                                  None if free else float(alpha),
                                  int(nwalkers), int(steps))
    chain, _ = run(jax.random.PRNGKey(seed), jnp.asarray(p0),
                   jnp.asarray(win), jnp.asarray(x_t), jnp.asarray(x_f),
                   jnp.asarray(tmax), jnp.asarray(fmax),
                   jnp.asarray(sigma))
    med, std, _ = _posterior_summary(chain, burn, ndim)
    sp = ScintParams(tau=med[0], tauerr=std[0], dnu=med[1],
                     dnuerr=std[1], amp=med[2], wn=med[3],
                     talpha=med[5] if free else alpha,
                     talphaerr=std[5] if free else None,
                     redchi=float(np.asarray(lm_sp.redchi)))
    out = (sp, float(med[4]), float(std[4]))
    if return_chain:
        return out + (np.asarray(chain[burn:]),)
    return out


@functools.lru_cache(maxsize=32)
def _sspec_sampler_cached(nt: int, nf: int, alpha: float | None,
                          nwalkers: int, steps: int):
    """Sampler for the Fourier-domain (sspec-method) posterior."""
    import jax.numpy as jnp

    from ..models.acf_models import scint_sspec_model

    free = alpha is None

    def log_prob(p, x_t, x_f, y, sigma):
        tau, dnu, amp, wn = p[0], p[1], p[2], p[3]
        a_ = p[4] if free else alpha
        inside = (tau > 0) & (dnu > 0) & (amp > 0) & (wn >= 0)
        if free:
            inside = inside & (a_ > 0) & (a_ < 8.0)
        m = scint_sspec_model(x_t, x_f, tau, dnu, amp, wn, a_, xp=jnp)
        chi2 = jnp.sum(((y - m) / sigma) ** 2)
        return jnp.where(inside, -0.5 * chi2, -jnp.inf)

    return _build_sampler(5 if free else 4, nwalkers, steps, 2.0,
                          log_prob)


def fit_scint_params_sspec_mcmc(acf2d, dt, df, nchan: int, nsub: int,
                                alpha: float | None = 5 / 3,
                                nwalkers: int = 32, steps: int = 600,
                                burn: int = 300, seed: int = 0,
                                return_chain: bool = False):
    """Posterior tau/dnu in the Fourier (power-spectrum) domain — the
    ``mcmc=True`` analogue of fit_scint_params_sspec (the reference's
    unfinished 'sspec' method, dynspec.py:953-957)."""
    import jax
    import jax.numpy as jnp

    from ..models.acf_models import mirror_spectrum, scint_sspec_model
    from .scint_fit import acf_cuts, fit_scint_params_sspec

    if burn >= steps:
        raise ValueError(f"burn ({burn}) must be < steps ({steps})")
    free = alpha is None
    lm = fit_scint_params_sspec(acf2d, dt, df, nchan, nsub, alpha=alpha,
                                backend="numpy")
    alpha_best = float(np.asarray(lm.talpha))
    p_best = np.array([float(lm.tau), float(lm.dnu), float(lm.amp),
                       float(lm.wn)] + ([alpha_best] if free else []))
    ndim = len(p_best)
    a = np.asarray(acf2d, dtype=np.float64)
    x_t, y_t, x_f, y_f = acf_cuts(a, dt, abs(df), nchan, nsub, xp=np)
    y = np.concatenate([mirror_spectrum(y_t, xp=np),
                        mirror_spectrum(y_f, xp=np)])
    resid = y - scint_sspec_model(x_t, x_f, *p_best[:4], alpha_best,
                                  xp=np)
    sigma = max(float(np.std(resid)), 1e-12)

    rng = np.random.default_rng(seed)
    p0 = p_best * (1.0 + 0.01 * rng.standard_normal((nwalkers, ndim)))
    p0 = np.abs(p0) + 1e-12
    run = _sspec_sampler_cached(len(x_t), len(x_f),
                                None if free else float(alpha),
                                int(nwalkers), int(steps))
    chain, _ = run(jax.random.PRNGKey(seed), jnp.asarray(p0),
                   jnp.asarray(x_t), jnp.asarray(x_f), jnp.asarray(y),
                   jnp.asarray(sigma))
    med, std, _ = _posterior_summary(chain, burn, ndim)
    out = ScintParams(tau=med[0], tauerr=std[0], dnu=med[1],
                      dnuerr=std[1], amp=med[2], wn=med[3],
                      talpha=med[4] if free else alpha,
                      talphaerr=std[4] if free else None,
                      redchi=float(np.asarray(lm.redchi)))
    if return_chain:
        return out, np.asarray(chain[burn:])
    return out


def fit_arc_curvature_mcmc(eta_obs, mjds, pars: dict, raj: float,
                           decj: float,
                           fit_keys=("s", "vism_psi"), etaerr=None,
                           nwalkers: int = 32, steps: int = 800,
                           burn: int = 400, seed: int = 0,
                           return_chain: bool = False):
    """Posterior over screen parameters from a curvature time series —
    the ``mcmc=True`` analogue of fit.fit_arc_curvature (reference
    surface: the lmfit-emcee option of its arc_curvature residuals,
    scint_models.py:266-315).

    Uniform box priors from the fitter's bounds; the likelihood noise
    scale comes from ``etaerr`` when given, else from the LM solution's
    residual std.  Returns (best dict, errors dict, post-burn chain |
    None) with posterior medians/stds for the fitted keys.
    """
    import jax
    import jax.numpy as jnp

    from ..astro import get_earth_velocity, get_true_anomaly
    from ..models.velocity import arc_curvature_residuals
    from .curvature_fit import _BOUNDS, fit_arc_curvature

    if burn >= steps:
        raise ValueError(f"burn ({burn}) must be < steps ({steps})")
    fit_keys = tuple(fit_keys)
    eta_obs = np.asarray(eta_obs, dtype=np.float64)
    mjds = np.asarray(mjds, dtype=np.float64)
    best0, _, _ = fit_arc_curvature(eta_obs, mjds, pars, raj, decj,
                                    fit_keys=fit_keys, etaerr=etaerr,
                                    backend="numpy")
    nu = (get_true_anomaly(mjds, pars) if "PB" in pars
          else np.zeros_like(mjds))
    v_ra, v_dec = get_earth_velocity(mjds, raj, decj)
    # the noise scale enters through sigma; the residuals themselves
    # stay unweighted
    weights = None
    if etaerr is not None:
        sigma = np.asarray(etaerr, dtype=np.float64)
    else:
        # unweighted residuals at the LM optimum set the noise scale
        resid0 = arc_curvature_residuals(best0, eta_obs, None, nu, v_ra,
                                         v_dec, xp=np)
        sigma = max(float(np.std(np.asarray(resid0))), 1e-12)
    fixed = {k: v for k, v in pars.items() if k not in fit_keys}
    lo = np.array([_BOUNDS[k][0] for k in fit_keys])
    hi = np.array([_BOUNDS[k][1] for k in fit_keys])

    def log_prob(p, eta, nu_, vra, vdec, sig):
        trial = dict(fixed, **{k: p[i] for i, k in enumerate(fit_keys)})
        r = arc_curvature_residuals(trial, eta, weights, nu_, vra, vdec,
                                    xp=jnp)
        chi2 = jnp.sum((r / sig) ** 2)
        inside = jnp.all((p > jnp.asarray(lo)) & (p < jnp.asarray(hi)))
        return jnp.where(inside, -0.5 * chi2, -jnp.inf)

    ndim = len(fit_keys)
    rng = np.random.default_rng(seed)
    p_best = np.array([best0[k] for k in fit_keys])
    span = hi - lo
    p0 = np.clip(p_best + 0.01 * span
                 * rng.standard_normal((nwalkers, ndim)),
                 lo + 1e-9 * span, hi - 1e-9 * span)
    chain, _ = ensemble_sample(
        log_prob, p0, key=jax.random.PRNGKey(seed), steps=steps,
        data_args=(jnp.asarray(eta_obs), jnp.asarray(nu),
                   jnp.asarray(v_ra), jnp.asarray(v_dec),
                   jnp.asarray(sigma)))
    med, std, _ = _posterior_summary(chain, burn, ndim)
    best = dict(best0)
    errors = {}
    for i, k in enumerate(fit_keys):
        best[k] = float(med[i])
        errors[k] = float(std[i])
    if return_chain:
        return best, errors, np.asarray(chain[burn:])
    return best, errors, None


@functools.lru_cache(maxsize=32)
def _scint_sampler_cached(nt: int, nf: int, alpha: float | None,
                          nwalkers: int, steps: int):
    """Sampler for the scint-params posterior, cached on static shapes
    only; the per-epoch data (lags, ACF cuts, noise scale) are traced
    arguments, so surveys over many epochs reuse one compiled program.
    ``alpha=None`` samples the power-law index as a fifth dimension."""
    import jax.numpy as jnp

    from ..models.acf_models import scint_acf_model

    free = alpha is None

    def log_prob(p, x_t, x_f, y, sigma):
        tau, dnu, amp, wn = p[0], p[1], p[2], p[3]
        a_ = p[4] if free else alpha
        inside = (tau > 0) & (dnu > 0) & (amp > 0) & (wn >= 0)
        if free:
            inside = inside & (a_ > 0) & (a_ < 8.0)
        model = scint_acf_model(x_t, x_f, tau, dnu, amp, wn, a_,
                                xp=jnp)
        chi2 = jnp.sum(((y - model) / sigma) ** 2)
        return jnp.where(inside, -0.5 * chi2, -jnp.inf)

    return _build_sampler(5 if free else 4, nwalkers, steps, 2.0, log_prob)


def fit_scint_params_mcmc(acf2d, dt, df, nchan: int, nsub: int,
                          alpha: float | None = 5 / 3, nwalkers: int = 32,
                          steps: int = 600, burn: int = 300,
                          seed: int = 0, return_chain: bool = False):
    """Posterior tau/dnu/amp/wn via ensemble MCMC around the LM solution
    (the reference's ``get_scint_params(mcmc=True)``, dynspec.py:989-992).

    Gaussian likelihood on the 1-D ACF cuts with the noise scale taken
    from the LM best fit's residual; positivity priors.  Returns a
    :class:`ScintParams` with posterior medians and stds (and the
    post-burn chain [steps-burn, nwalkers, 4] when ``return_chain``);
    ``redchi`` is the deterministic LM fit's reduced chi-square (the
    posterior itself has no independent noise estimate to judge with).
    """
    import jax
    import jax.numpy as jnp

    from ..models.acf_models import scint_acf_model
    from .scint_fit import acf_cuts, fit_scint_params

    if burn >= steps:
        raise ValueError(f"burn ({burn}) must be < steps ({steps})")

    # start from the deterministic fit
    free = alpha is None
    lm = fit_scint_params(acf2d, dt, df, nchan, nsub, alpha=alpha,
                          backend="numpy")
    alpha_best = float(np.asarray(lm.talpha))
    p_best = np.array([float(lm.tau), float(lm.dnu), float(lm.amp),
                       float(lm.wn)] + ([alpha_best] if free else []))
    ndim = len(p_best)
    x_t, y_t, x_f, y_f = acf_cuts(np.asarray(acf2d, dtype=np.float64),
                                  dt, df, nchan, nsub, xp=np)
    y = np.concatenate([y_t, y_f])
    resid = y - scint_acf_model(x_t, x_f, *p_best[:4], alpha_best, xp=np)
    sigma = max(float(np.std(resid)), 1e-12)

    rng = np.random.default_rng(seed)
    p0 = p_best * (1.0 + 0.01 * rng.standard_normal((nwalkers, ndim)))
    p0 = np.abs(p0) + 1e-12
    run = _scint_sampler_cached(len(x_t), len(x_f),
                                None if free else float(alpha),
                                int(nwalkers), int(steps))
    chain, _ = run(jax.random.PRNGKey(seed), jnp.asarray(p0),
                   jnp.asarray(x_t), jnp.asarray(x_f), jnp.asarray(y),
                   jnp.asarray(sigma))
    med, std, _ = _posterior_summary(chain, burn, ndim)
    out = ScintParams(tau=med[0], tauerr=std[0], dnu=med[1], dnuerr=std[1],
                      amp=med[2], wn=med[3],
                      talpha=med[4] if free else alpha,
                      talphaerr=std[4] if free else None,
                      redchi=float(np.asarray(lm.redchi)))
    if return_chain:
        return out, np.asarray(chain[burn:])
    return out


def fit_scint_params_mcmc_batch(acf2d_batch, dt, df, nchan: int, nsub: int,
                                alpha: float | None = 5 / 3,
                                nwalkers: int = 32, steps: int = 600,
                                burn: int = 300, seed: int = 0,
                                lm_steps: int = 20, mesh=None,
                                return_chain: bool = False):
    """Batched posterior tau/dnu/amp/wn over B epochs in ONE device
    program: the stretch-move sampler of :func:`fit_scint_params_mcmc`
    vmapped over the epoch axis (the module docstring's "itself
    vmappable over epochs", made API), started from the vmapped
    fixed-iteration LM fit — the SPMD analogue of looping the
    reference's ``get_scint_params(mcmc=True)`` (dynspec.py:989-992)
    over files one at a time.

    ``mesh`` shards the epoch axis over the mesh's ``data`` axis:
    walker updates are per-epoch element-wise work plus per-epoch lag
    reductions, so the program is embarrassingly parallel and the
    input sharding alone distributes it (no collectives).  Degenerate
    (NaN-LM) lanes propagate NaN posteriors — the batch driver's
    quarantine convention.

    Returns :class:`ScintParams` of [B] posterior medians/stds (and
    the post-burn chain [B, steps-burn, nwalkers, ndim] when
    ``return_chain``); ``redchi`` carries the LM fits' values.
    """
    import jax
    import jax.numpy as jnp

    from ..models.acf_models import scint_acf_model
    from .scint_fit import acf_cuts, fit_scint_params_batch

    if burn >= steps:
        raise ValueError(f"burn ({burn}) must be < steps ({steps})")
    acf_np = np.asarray(acf2d_batch, dtype=np.float64)
    B = acf_np.shape[0]
    free = alpha is None

    lm = fit_scint_params_batch(acf2d_batch, dt, df, nchan, nsub,
                                alpha=alpha, steps=lm_steps)
    cols = [np.asarray(lm.tau, dtype=np.float64),
            np.asarray(lm.dnu, dtype=np.float64),
            np.asarray(lm.amp, dtype=np.float64),
            np.asarray(lm.wn, dtype=np.float64)]
    alpha_best = (np.asarray(lm.talpha, dtype=np.float64) if free
                  else np.full(B, float(alpha)))
    if free:
        cols.append(alpha_best)
    p_best = np.stack(cols, axis=1)                       # [B, ndim]
    ndim = p_best.shape[1]

    x_t, y_t, x_f, y_f = acf_cuts(acf_np, dt, df, nchan, nsub, xp=np)
    y = np.concatenate([y_t, y_f], axis=-1)               # [B, L]
    # per-epoch noise scale from the LM best fit's residual (the same
    # convention as the single-epoch path); cheap host loop — the model
    # evaluation is [L]-sized
    sigma = np.empty(B)
    for b in range(B):
        m = scint_acf_model(x_t, x_f, *p_best[b, :4], alpha_best[b],
                            xp=np)
        sigma[b] = max(float(np.std(y[b] - m)), 1e-12)

    rng = np.random.default_rng(seed)
    p0 = p_best[:, None, :] * (
        1.0 + 0.01 * rng.standard_normal((B, nwalkers, ndim)))
    p0 = np.abs(p0) + 1e-12

    run = _scint_sampler_cached(len(x_t), len(x_f),
                                None if free else float(alpha),
                                int(nwalkers), int(steps))
    vrun = jax.vmap(run, in_axes=(0, 0, None, None, 0, 0))
    keys = jax.random.split(jax.random.PRNGKey(seed), B)
    args = [keys, jnp.asarray(p0), jnp.asarray(x_t), jnp.asarray(x_f),
            jnp.asarray(y), jnp.asarray(sigma)]
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import DATA_AXIS

        shard = NamedSharding(mesh, P(DATA_AXIS))
        for i in (0, 1, 4, 5):  # epoch-axis leaves; lag axes replicate
            args[i] = jax.device_put(args[i], shard)
    chain, lps = vrun(*args)
    chain = np.asarray(chain)                  # [B, steps, nw, ndim]
    post = chain[:, burn:].reshape(B, -1, ndim)
    med = np.median(post, axis=1)
    std = np.std(post, axis=1)
    # quarantine: a lane whose LM start was degenerate (NaN params —
    # the batched LM clamps tau/dnu to a positivity floor but NaNs
    # amp/wn on dead epochs) never leaves -inf log-prob, so its
    # "posterior" is just the jittered start.  NaN-poison it like
    # every other batched fitter instead of reporting the clamp floor.
    lp_post = np.asarray(lps)[:, burn:]
    dead = (~np.all(np.isfinite(p_best), axis=1)
            | ~np.isfinite(sigma)
            | ~np.any(np.isfinite(lp_post).reshape(B, -1), axis=1))
    med[dead] = np.nan
    std[dead] = np.nan
    out = ScintParams(
        tau=med[:, 0], tauerr=std[:, 0], dnu=med[:, 1], dnuerr=std[:, 1],
        amp=med[:, 2], wn=med[:, 3],
        talpha=med[:, 4] if free else np.full(B, float(alpha)),
        talphaerr=std[:, 4] if free else None,
        redchi=np.asarray(lm.redchi))
    if return_chain:
        return out, chain[:, burn:]
    return out
