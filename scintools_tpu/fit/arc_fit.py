"""Scintillation-arc curvature fitting.

Reference: ``Dynspec.fit_arc`` (dynspec.py:414-785) and
``Dynspec.norm_sspec`` (dynspec.py:787-926).  Two methods:

* ``norm_sspec`` (flagship): normalise the Doppler axis of every delay row
  by ``sqrt(tdel/eta_min)``, delay-scrunch to a 1-D power-vs-normalised-fdop
  profile, fold the two arms, map normalised fdop back to an eta grid, and
  fit a parabola around the smoothed peak (dynspec.py:661-771, 787-926).
* ``gridmax``: for each trial eta, sample the secondary spectrum along
  ``tdel = eta*fdop^2`` with bilinear interpolation and find the eta
  maximising mean power (dynspec.py:516-659).

The numpy path replicates the reference step-for-step (minus plotting),
including its quirks: the double delmax frequency adjustment
(dynspec.py:428-429 then 796-797), the value-matching peak lookup
``argmin(|filt - max_inrange|)`` (dynspec.py:698), the asymmetric walk
guard ``ind + ind1 < len-1`` on the *left* walk (dynspec.py:581-582), and
the +2 dB profile shift when the profile at normalised fdop=1 is negative
(dynspec.py:864-866).

The jax path (:func:`make_arc_fitter`) is the fixed-shape SPMD rebuild:
row-normalisation becomes vmapped uniform-grid linear interpolation
(index arithmetic, no searchsorted; identical values to masked interp
because linear interpolation is local and scale-invariant, and the fdop
grid from sspec_axes is uniform), NaN masks replace boolean compaction,
the -3 dB walks become
first-crossing reductions, and the windowed parabola fit uses 0/1 weights —
so one jit compiles the whole measurement for a [B, nr, nc] batch of
epochs.  Agreement with the numpy path is asserted on synthetic arcs in
tests (not bit-equal: the walk guard quirk and boundary smoothing differ;
documented there).
"""

from __future__ import annotations

import dataclasses
import functools
import warnings
from typing import Any

import numpy as np
from scipy.ndimage import map_coordinates
from scipy.signal import savgol_filter

from .. import obs
from ..backend import resolve
from ..data import ArcFit, SecSpec
from ..models.parabola import fit_log_parabola, fit_parabola

C_M_S = 299792458.0


@dataclasses.dataclass(frozen=True)
class NormSspec:
    """Normalised secondary spectrum (dynspec.py:923-925)."""

    normsspec: Any      # [ntdel, nfdop]
    normsspecavg: Any   # [nfdop] delay-scrunched profile
    powerspec: Any      # [ntdel] fdop-scrunched power spectrum
    tdel: Any           # [ntdel] cut delay (or beta) axis
    fdopnew: Any        # [nfdop] normalised fdop axis


def _beta_to_eta_factor(freq: float, ref_freq: float) -> float:
    """Unit conversion used when fitting in tdel rather than beta space
    (dynspec.py:494-499)."""
    return C_M_S * 1e6 / ((ref_freq * 1e6) ** 2)


def norm_sspec(sec: SecSpec, freq: float, eta: float, delmax=None,
               startbin: int = 1, maxnormfac: float = 2, cutmid: int = 3,
               numsteps: int | None = None, ref_freq: float = 1400.0
               ) -> NormSspec:
    """Normalise the fdop axis by the arc curvature (dynspec.py:787-926,
    compute only).  ``eta`` is in the units of ``sec``'s delay axis (beta
    for lamsteps, converted internally otherwise, dynspec.py:820-825)."""
    sspec = np.array(sec.sspec, dtype=np.float64)
    yaxis = np.asarray(sec.beta if sec.lamsteps else sec.tdel,
                       dtype=np.float64)
    tdel_axis = np.asarray(sec.tdel)
    fdop = np.asarray(sec.fdop, dtype=np.float64)

    delmax = np.max(tdel_axis) if delmax is None else delmax
    delmax = delmax * (ref_freq / freq) ** 2

    if not sec.lamsteps:
        eta = eta / (freq / ref_freq) ** 2
        eta = eta * _beta_to_eta_factor(freq, ref_freq)

    ind = np.argmin(np.abs(tdel_axis - delmax))
    sspec = sspec[startbin:ind, :]
    nr, nc = sspec.shape
    sspec[:, int(nc / 2 - np.floor(cutmid / 2)):
          int(nc / 2 + np.floor(cutmid / 2))] = np.nan
    tdel = yaxis[startbin:ind]

    maxfdop = maxnormfac * np.sqrt(tdel[-1] / eta)
    if maxfdop > np.max(fdop):
        maxfdop = np.max(fdop)
    nfdop = (2 * len(fdop[np.abs(fdop) <= maxfdop]) if numsteps is None
             else int(numsteps))
    fdopnew = np.linspace(-maxnormfac, maxnormfac, nfdop)

    norm_rows = []
    for ii in range(len(tdel)):
        itdel = tdel[ii]
        imaxfdop = maxnormfac * np.sqrt(itdel / eta)
        mask = np.abs(fdop) <= imaxfdop
        ifdop = fdop[mask] / np.sqrt(itdel / eta)
        isspec = sspec[ii, mask]
        norm_rows.append(np.interp(fdopnew, ifdop, isspec))
    norm_arr = np.array(norm_rows)
    # columns fully inside the cutmid notch are all-NaN by construction
    # (the reference produces the same NaN means, warning unsuppressed)
    with warnings.catch_warnings():
        warnings.filterwarnings("ignore", message="Mean of empty slice")
        isspecavg = np.nanmean(norm_arr, axis=0)
        powerspec = np.nanmean(norm_arr, axis=1)
    ind1 = np.argmin(np.abs(fdopnew - 1) - 2)
    if isspecavg[ind1] < 0:
        isspecavg = isspecavg + 2  # reference's dB-offset quirk
    return NormSspec(normsspec=norm_arr, normsspecavg=isspecavg,
                     powerspec=powerspec, tdel=tdel, fdopnew=fdopnew)


def norm_sspec_row_window(tdel_axis, freq: float, ref_freq: float = 1400.0,
                          delmax: float | None = None
                          ) -> tuple[int, int, float]:
    """The delay-row window the batched norm_sspec fitter consumes:
    ``(ind, ind_norm, dmax_raw)`` where ``ind`` is the fit-level delay
    cut index, ``ind_norm`` the row-normalisation cut (the reference's
    double frequency adjustment, dynspec.py:428-429 then 796-797), and
    ``dmax_raw`` the pre-adjustment delmax (``max(tdel)`` when None).

    Single source of truth shared by :func:`make_arc_fitter`'s builder
    and the pipeline driver's fused sspec crop
    (``PipelineConfig.sspec_crop``): the driver crops the secondary
    spectrum to ``max(ind, ind_norm) + 1`` rows and rebuilds the fitter
    on the cropped axes with ``delmax=dmax_raw`` pinned, which this
    shared rule guarantees resolves to the SAME indices."""
    tdel_axis = np.asarray(tdel_axis, dtype=np.float64)
    dmax_raw = float(np.max(tdel_axis)) if delmax is None else float(delmax)
    dmax = dmax_raw * (ref_freq / freq) ** 2
    dmax_norm = dmax * (ref_freq / freq) ** 2
    ind = int(np.argmin(np.abs(tdel_axis - dmax)))
    ind_norm = int(np.argmin(np.abs(tdel_axis - dmax_norm)))
    return ind, ind_norm, dmax_raw


def _noise_estimate(sspec: np.ndarray, cutmid: int, xp=np) -> float:
    """Noise from the outer Doppler quadrants at high delay
    (dynspec.py:446-451)."""
    nr, nc = sspec.shape[-2], sspec.shape[-1]
    a = sspec[..., nr // 2:, int(nc / 2 + np.ceil(cutmid / 2)):]
    b = sspec[..., nr // 2:, : int(nc / 2 - np.floor(cutmid / 2))]
    both = xp.concatenate(
        [a.reshape(a.shape[:-2] + (-1,)), b.reshape(b.shape[:-2] + (-1,))],
        axis=-1)
    return xp.std(both, axis=-1)


def _walk(filt: np.ndarray, ind: int, threshold: float) -> tuple[int, int]:
    """The reference's peak-window walks (dynspec.py:702-718): step left
    while the smoothed power stays above threshold (guarded, quirkily, on
    ind+ind1), then right."""
    n = len(filt)
    power, ind1 = filt[ind], 1
    while power > threshold and ind + ind1 < n - 1:
        ind1 += 1
        power = filt[ind - ind1]
    power, ind2 = filt[ind], 1
    while power > threshold and ind + ind2 < n - 1:
        ind2 += 1
        power = filt[ind + ind2]
    return ind1, ind2


# Relative power variation below which a parabola-fit window counts as
# flat (degenerate).  Degenerate windows sit ~14 orders below this (f.p.
# dust on a constant profile); real weak arcs sit ~7 orders above
# (>= 0.01 dB structure), so both backends — whose window values are
# bit-identical — always make the same call.
_FLAT_WINDOW_TOL = 1e-9


def _check_profile_size(profile, nsmooth: int) -> None:
    """Informative failure for profiles too short to smooth/fit
    (np.size: robust to the 0-d arrays `.squeeze()` produces when only
    one point survives masking).  savgol accepts window_length == size,
    so only strictly smaller profiles are rejected."""
    if np.size(profile) < nsmooth:
        raise ValueError(
            f"curvature profile has only {np.size(profile)} valid points "
            f"(< nsmooth={nsmooth}) — secondary spectrum too small or "
            f"too masked to fit an arc")


def _measure_peak(eta_array, power, filt, noise, constraint,
                  low_power_diff, high_power_diff, noise_error, lamsteps,
                  log_fit: bool) -> ArcFit:
    """Constrained peak search + power-drop walks + (log-)parabola fit on
    a precomputed power-vs-curvature profile (dynspec.py:693-744).

    Shared by fit_arc's norm_sspec and gridmax branches and by the
    multi-arc driver, which measures several windows of ONE profile.
    """
    inrange = np.argwhere((eta_array > constraint[0])
                          * (eta_array < constraint[1]))
    if inrange.size == 0:
        raise ValueError(f"no eta grid points inside constraint "
                         f"{tuple(constraint)}")
    peak_ind = int(np.argmin(np.abs(filt - np.max(filt[inrange]))))
    max_power = filt[peak_ind]

    # -3 dB on the low-curvature side, -1.5 dB on the high side
    i1, _ = _walk(filt, peak_ind, max_power + low_power_diff)
    _, i2 = _walk(filt, peak_ind, max_power + high_power_diff)
    # NOTE: the slice start may be negative when the walk overshoots a
    # peak near the profile edge; python then wraps it, which for the
    # usual overshoot-to-the-end case selects nearly the whole profile.
    # The reference relies on exactly this behaviour (dynspec.py:638-641),
    # so it is kept bit-for-bit; only the truly crashing case (wrap
    # produces an EMPTY window, a deep numpy reduction error in the
    # reference) is turned into an informative failure.
    xdata = eta_array[peak_ind - i1: peak_ind + i2]
    ydata = power[peak_ind - i1: peak_ind + i2]
    if xdata.size < 3:
        # < 3 points under-determines the parabola (np.polyfit deg=2)
        # and the forward-parabola gradient check needs 3; seen on real
        # dirty data when RFI zapping narrows the -3 dB window to a
        # couple of bins (tests/data fixture).  The reference would
        # crash inside np.gradient here; we quarantine with a reason.
        raise ValueError(
            f"arc peak at grid index {peak_ind} leaves only "
            f"{xdata.size} point(s) for the parabola fit — peak is at "
            f"the eta-grid edge or the power-drop window collapsed "
            f"(widen etamin/etamax, the constraint window, or "
            f"low_power_diff)")
    # Flat-window degeneracy guard (INTENDED deviation from the
    # reference, which happily returns the vertex): when the windowed
    # power is constant to ~f.p. dust, the parabola's a and b are pure
    # rounding noise, so the vertex, its sign, and even the
    # forward-parabola check are nondeterministic across BLAS
    # implementations (the reference's np.polyfit SVD gives a third
    # value again).  This happens systematically on non-lamsteps
    # norm_sspec fits: the double eta conversion
    # (dynspec.py:498-499 then 820-825) shrinks eta by beta_to_eta^2
    # ~ 2e-8, so every resample scale is ~4 orders past the fdop grid
    # and all bins clamp to the row-edge mean.  The threshold is
    # decided on profile values that are BIT-IDENTICAL across
    # backends, so numpy's raise and the batched fitter's NaN
    # quarantine always agree.
    if np.ptp(ydata) <= _FLAT_WINDOW_TOL * max(1.0,
                                               abs(np.max(ydata))):
        raise ValueError(
            "curvature profile is flat across the fit window to "
            "floating-point precision — the parabola vertex would be "
            "rounding noise (non-lamsteps norm_sspec fits hit this "
            "systematically: the reference's double eta conversion "
            "clamps every resampled bin to the row edges)")
    fitter = fit_log_parabola if log_fit else fit_parabola
    yfit, eta, etaerr_fit = fitter(xdata, ydata, xp=np)
    if np.mean(np.gradient(np.diff(yfit))) > 0:
        raise ValueError("Fit returned a forward parabola.")

    etaerr = etaerr_fit
    if noise_error:
        j1, j2 = _walk(filt, peak_ind, max_power - noise)
        win = eta_array[peak_ind - j1: peak_ind + j2]  # wrap as reference
        etaerr = np.ptp(win) / 2 if win.size else np.nan

    return ArcFit(eta=eta, etaerr=etaerr, etaerr2=etaerr_fit,
                  lamsteps=lamsteps, profile_eta=eta_array,
                  profile_power=power, profile_power_filt=filt,
                  noise=noise)


def _attach_arms(fit: ArcFit, left_fn, right_fn) -> ArcFit:
    """Attach independent left/right-arm measurements to a combined fit.
    A degenerate arm (forward parabola / too-short profile) yields NaN for
    that arm rather than failing the primary measurement."""
    def _arm(fn):
        try:
            f = fn()
            return float(f.eta), float(f.etaerr)
        except ValueError:
            return float("nan"), float("nan")

    el, eel = _arm(left_fn)
    er, eer = _arm(right_fn)
    return dataclasses.replace(fit, eta_left=el, etaerr_left=eel,
                               eta_right=er, etaerr_right=eer)


@obs.traced("fit.arc")
def fit_arc(sec: SecSpec, freq: float, method: str = "norm_sspec",
            delmax=None, numsteps: int = 10000, startbin: int = 3,
            cutmid: int = 3, etamax=None, etamin=None,
            low_power_diff: float = -3.0, high_power_diff: float = -1.5,
            ref_freq: float = 1400.0, constraint=(0, np.inf),
            nsmooth: int = 5, noise_error: bool = True, asymm: bool = False,
            backend: str = "numpy") -> ArcFit:
    """Find the arc curvature maximising power along ``tdel = eta fdop^2``
    (dynspec.py:414-785, compute only; primary arc).

    ``asymm=True`` additionally fits the left and right fdop arms
    independently (``eta_left/eta_right`` on the result) on both
    backends (vmappable on jax).  The reference plumbs this flag but a
    copy-paste bug feeds the combined profile to both arm fits
    (dynspec.py:567-568) and the per-arm values are only plotted, never
    returned — completed here."""
    backend = resolve(backend)
    if asymm and method == "thetatheta":
        raise ValueError("asymm=True is not meaningful for "
                         "method='thetatheta' (the theta-theta transform "
                         "uses both arms jointly); use 'gridmax' or "
                         "'norm_sspec'")
    if method == "thetatheta":
        # eigenvector-based measurement (beyond-reference; see
        # fit.thetatheta): needs an explicit eta bracket, further
        # narrowed by any constraint window
        from .thetatheta import fit_arc_thetatheta

        if etamin is None or etamax is None:
            raise ValueError("method='thetatheta' needs explicit "
                             "etamin/etamax bracketing the arc")
        lo = max(float(etamin), float(constraint[0]))
        hi = min(float(etamax), float(constraint[1]))
        if not lo < hi:
            raise ValueError(f"empty eta bracket after intersecting "
                             f"[{etamin}, {etamax}] with constraint "
                             f"{tuple(constraint)}")
        eta, etaerr, etas, conc = fit_arc_thetatheta(
            sec, lo, hi, n_eta=int(numsteps), startbin=startbin,
            cutmid=cutmid, backend=backend)
        return ArcFit(eta=eta, etaerr=etaerr, etaerr2=etaerr,
                      lamsteps=sec.lamsteps, profile_eta=etas,
                      profile_power=conc, profile_power_filt=conc)
    if backend == "jax" and method in ("norm_sspec", "gridmax"):
        fitter = make_arc_fitter(
            fdop=np.asarray(sec.fdop), yaxis=np.asarray(
                sec.beta if sec.lamsteps else sec.tdel),
            tdel=np.asarray(sec.tdel), freq=freq, lamsteps=sec.lamsteps,
            method=method, delmax=delmax, numsteps=int(numsteps),
            startbin=startbin, cutmid=cutmid, etamax=etamax, etamin=etamin,
            low_power_diff=low_power_diff, high_power_diff=high_power_diff,
            ref_freq=ref_freq, constraint=tuple(constraint),
            nsmooth=nsmooth, noise_error=noise_error, asymm=asymm)
        import jax.numpy as jnp

        batch = fitter(jnp.asarray(sec.sspec)[None])

        def lane0(x):
            return None if x is None else x[0]

        return ArcFit(eta=batch.eta[0], etaerr=batch.etaerr[0],
                      etaerr2=batch.etaerr2[0], lamsteps=batch.lamsteps,
                      profile_eta=batch.profile_eta,
                      profile_power=batch.profile_power[0],
                      profile_power_filt=batch.profile_power_filt[0],
                      noise=batch.noise[0],
                      eta_left=lane0(batch.eta_left),
                      etaerr_left=lane0(batch.etaerr_left),
                      eta_right=lane0(batch.eta_right),
                      etaerr_right=lane0(batch.etaerr_right))
    sspec = np.array(sec.sspec, dtype=np.float64)
    tdel_axis = np.asarray(sec.tdel)
    fdop = np.asarray(sec.fdop, dtype=np.float64)
    lamsteps = sec.lamsteps

    delmax = np.max(tdel_axis) if delmax is None else delmax
    delmax = delmax * (ref_freq / freq) ** 2

    yaxis = np.asarray(sec.beta if lamsteps else sec.tdel, dtype=np.float64)
    ind = np.argmin(np.abs(tdel_axis - delmax))
    ymax = yaxis[ind] if lamsteps else delmax

    noise = float(_noise_estimate(sspec, cutmid))

    nr, nc = sspec.shape
    sspec[0:startbin, :] = np.nan
    sspec[:, int(nc / 2 - np.floor(cutmid / 2)):
          int(nc / 2 + np.ceil(cutmid / 2))] = np.nan
    sspec = sspec[0:ind, :]
    yaxis_cut = yaxis[0:ind]
    noise = noise / len(yaxis_cut[startbin:])

    if etamax is None:
        etamax = ymax / ((fdop[1] - fdop[0]) * cutmid) ** 2
    if etamin is None:
        etamin = (yaxis_cut[1] - yaxis_cut[0]) * startbin / np.max(fdop) ** 2

    constraint = np.asarray(constraint, dtype=np.float64)
    if not lamsteps:
        b2e = _beta_to_eta_factor(freq, ref_freq)
        etamax = etamax / (freq / ref_freq) ** 2 * b2e
        etamin = etamin / (freq / ref_freq) ** 2 * b2e
        constraint = constraint / (freq / ref_freq) ** 2 * b2e

    sqrt_eta_all = np.linspace(np.sqrt(etamin), np.sqrt(etamax),
                               int(numsteps))
    sqrt_eta = sqrt_eta_all  # single-arc: full range
    numsteps_new = len(sqrt_eta)

    if method == "norm_sspec":
        ns = norm_sspec(sec, freq, eta=etamin, delmax=delmax,
                        startbin=startbin, maxnormfac=1, cutmid=cutmid,
                        numsteps=numsteps_new, ref_freq=ref_freq)
        prof = ns.normsspecavg.squeeze()
        n = len(prof)
        etafrac = np.linspace(-1, 1, n)
        ipos = np.argwhere(etafrac > 1 / (2 * n))
        ineg = np.argwhere(etafrac < -1 / (2 * n))
        etafrac_pos = 1 / etafrac[ipos].squeeze()

        def _measure_arm(arm_prof, log_fit=False):
            a = arm_prof.squeeze()
            valid = np.isfinite(a) * (~np.isnan(a))
            a = np.flip(a[valid], axis=0)
            ef = np.flip(etafrac_pos[valid], axis=0)
            ea = etamin * ef ** 2
            keep = np.argwhere(ea < etamax)
            ea = ea[keep].squeeze()
            a = a[keep].squeeze()
            _check_profile_size(a, nsmooth)
            filt = savgol_filter(a, nsmooth, 1)
            return _measure_peak(ea, a, filt, noise, constraint,
                                 low_power_diff, high_power_diff,
                                 noise_error, lamsteps, log_fit=log_fit)

        fit = _measure_arm((prof[ipos] + np.flip(prof[ineg], axis=0)) / 2)
        if asymm:
            fit = _attach_arms(fit,
                               lambda: _measure_arm(np.flip(prof[ineg],
                                                            axis=0)),
                               lambda: _measure_arm(prof[ipos]))
        return fit

    if method == "gridmax":
        x, y, z = fdop, yaxis_cut, sspec
        sumpow_l, sumpow_r, eta_list = [], [], []
        for se in sqrt_eta:
            ieta = se ** 2
            eta_list.append(ieta)
            ynew = ieta * x ** 2
            xpx = (x - x.min()) / (x.max() - x.min()) * z.shape[1]
            ynewpx = (ynew - ynew.min()) / (y.max() - ynew.min()) * z.shape[0]
            for side, store in ((x < 0, sumpow_l), (x > 0, sumpow_r)):
                sel = side & (ynew < y.max())
                coords = np.stack([ynewpx[sel], xpx[sel]])
                zn = map_coordinates(z, coords, order=1, cval=np.nan)
                store.append(np.mean(zn[~np.isnan(zn)]))
        eta_array = np.array(eta_list)

        def _measure_grid(pow_arr):
            ok = np.isfinite(pow_arr)
            ea, p = eta_array[ok], pow_arr[ok]
            _check_profile_size(p, nsmooth)
            filt = savgol_filter(p, nsmooth, 1)
            return _measure_peak(ea, p, filt, noise, constraint,
                                 low_power_diff, high_power_diff,
                                 noise_error, lamsteps, log_fit=True)

        fit = _measure_grid((np.array(sumpow_l) + np.array(sumpow_r)) / 2)
        if asymm:
            fit = _attach_arms(fit,
                               lambda: _measure_grid(np.array(sumpow_l)),
                               lambda: _measure_grid(np.array(sumpow_r)))
        return fit

    raise ValueError("unknown arc fitting method; choose from "
                     "'gridmax' or 'norm_sspec'")


# ---------------------------------------------------------------------------
# jax fixed-shape batched fitter
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _profile_tail(nsmooth, low_power_diff, high_power_diff, noise_error,
                  arc_tail="exact"):
    """Measurement-tail factory: masked peak search + power-drop walks +
    (log-)parabola fit over a power-vs-eta profile, with everything
    grid-shaped (the eta array, validity window, constraint mask)
    entering PER CALL -- axes-free, so the per-axes fitter
    (:func:`_make_arc_fitter_cached`, which bakes its static grids into
    the caller's trace) and the split pipeline's shape-stable back-end
    unit (:func:`make_profile_measurer`) share ONE implementation.

    Returns ``measure_profile(avg, valid, noise, ea, cmask, use_log)``
    -- the ``arc_tail="exact"`` compacted-array tail or the
    ``"fast"`` masked-reduction tail.
    """
    import jax
    import jax.numpy as jnp

    from ..models.parabola import fit_parabola as _fitpar

    def measure_profile_fast(avg, valid, noise, ea, cmask, use_log):
        """Masked-reduction measurement tail (``arc_tail="fast"``).

        Same stages as the exact tail — smooth, constrained peak search,
        power-drop walks, (log-)parabola vertex fit, noise-crossing
        etaerr — but computed directly on the masked full grid instead
        of emulating the reference's compacted-array semantics
        (dynspec.py:580-618,702-744).  What it drops, and why it's
        cheaper: no stable-partition compaction (a full-length scatter +
        three gathers per measurement), no savgol edge linfits (a plain
        masked moving average at the boundaries too), no mod-wrap walk
        indexing (crossings found by masked min/max index reductions in
        original index space), and no scatter-back of the smoothed
        profile.  On diffuse arcs the walk endpoints can differ from the
        compacted-index walks by a few grid cells, moving eta by up to
        tens of percent of etaerr — the A/B contract (tests +
        benchmarks/profile_stages.py) is |eta_fast - eta_exact| within
        the fit's own etaerr, not bit equality.  Degenerate lanes NaN
        out under the same conditions as the exact tail.
        """
        from ..models.parabola import (fit_log_parabola_vertex,
                                       fit_parabola_vertex)

        n = avg.shape[0]
        idx = jnp.arange(n)
        nv = jnp.sum(valid)
        avg_z = jnp.where(valid, avg, 0.0)

        # masked moving average of width nsmooth: each point averages
        # its VALID neighbours; boundary points just see fewer of them
        kern = jnp.ones(nsmooth, dtype=avg.dtype)
        num = jnp.convolve(avg_z, kern, mode="same")
        den = jnp.convolve(valid.astype(avg.dtype), kern, mode="same")
        filt = jnp.where(valid, num / jnp.maximum(den, 1.0), jnp.nan)

        # constrained peak (argmax over valid & constraint)
        search = valid & jnp.asarray(cmask)
        peak_ind = jnp.argmax(jnp.where(search, filt, -jnp.inf))
        max_power = filt[peak_ind]

        def crossings(threshold):
            """Nearest valid grid points at/below ``threshold`` on each
            side of the peak, in original index space (replaces the
            exact tail's compacted-index walks)."""
            below = valid & (filt <= threshold)
            left = jnp.max(jnp.where(below & (idx < peak_ind), idx, -1))
            right = jnp.min(jnp.where(below & (idx > peak_ind), idx, n))
            return left, right

        l1, _ = crossings(max_power + low_power_diff)
        _, r2 = crossings(max_power + high_power_diff)
        # window includes the left crossing, excludes the right one —
        # the exact tail's slice convention (arr[peak-i1:peak+i2])
        wmask = valid & (idx >= jnp.maximum(l1, 0)) & (idx < r2)
        w = wmask.astype(avg.dtype)

        # shared vertex helpers (models/parabola.py): same pre-scaling
        # and error propagation as the exact tail's fit, with the
        # quadratic coefficient exposed — its sign IS the
        # forward-parabola check here (no windowed-gradient emulation)
        if use_log:
            a_c, _, eta, etaerr_fit = fit_log_parabola_vertex(
                ea, avg_z, w=w, xp=jnp)
        else:
            a_c, _, eta, etaerr_fit = fit_parabola_vertex(
                ea, avg_z, w=w, xp=jnp)

        etaerr = etaerr_fit
        if noise_error:
            ln, rn = crossings(max_power - noise)
            nmask = valid & (idx >= jnp.maximum(ln, 0)) & (idx < rn)
            lo_eta = jnp.min(jnp.where(nmask, ea, jnp.inf))
            hi_eta = jnp.max(jnp.where(nmask, ea, -jnp.inf))
            etaerr = jnp.where(jnp.any(nmask), (hi_eta - lo_eta) / 2,
                               jnp.nan)

        # degenerate lanes -> NaN, same conditions as the exact tail
        # (profile shorter than the smoother; empty constraint; <3
        # window points; forward parabola — here simply a_c > 0; flat
        # window)
        y_hi = jnp.max(jnp.where(wmask, avg_z, -jnp.inf))
        y_lo = jnp.min(jnp.where(wmask, avg_z, jnp.inf))
        flat = ((y_hi - y_lo)
                <= _FLAT_WINDOW_TOL * jnp.maximum(1.0, jnp.abs(y_hi)))
        bad = ((nv < nsmooth) | ~jnp.any(search)
               | (jnp.sum(w > 0) < 3) | (a_c > 0) | flat)
        eta = jnp.where(bad, jnp.nan, eta)
        etaerr = jnp.where(bad, jnp.nan, etaerr)
        etaerr_fit = jnp.where(bad, jnp.nan, etaerr_fit)

        avg_f = jnp.where(valid, avg, jnp.nan)
        return eta, etaerr, etaerr_fit, avg_f, filt

    def measure_profile(avg, valid, noise, ea, cmask, use_log):
        """Masked peak search + power-drop walks + (log-)parabola fit on
        a power-vs-eta profile — the jit-safe tail shared by both
        methods, emulating the numpy path's COMPACTED-array semantics
        exactly (dynspec.py:693-744): the serial reference chain drops
        invalid entries before smoothing/walking, and index-space walks
        on the compacted vs masked-full array diverge by 10-30% in eta
        on diffuse arcs.  Here the compaction is a cumsum-based stable
        partition (one scatter, no sort), the
        savgol is scipy's polyorder-1 'interp' filter at the dynamic
        boundary (interior = centred moving average; edges = linear LSQ
        over the first/last window evaluated at the edge positions),
        and the walks reproduce the reference's quirks: first examined
        offset is 2, the left walk guards on ind+ind1 like the right
        one, negative left indices wrap python-style, and the slice
        window EXCLUDES the right crossing point.

        Forward (upward-opening) parabolas NaN-poison eta/etaerr for
        EVERY fit — the jit-safe analogue of the numpy path's
        unconditional raise (dynspec.py:598-599).
        """
        n = avg.shape[0]
        idx = jnp.arange(n)
        # ---- compaction (numpy: a[valid], ascending eta) ---------------
        # stable partition (valid first, original order preserved) —
        # the same permutation argsort(where(valid, idx, n+idx)) gives,
        # computed as a cumsum + one scatter instead of an O(n log n)
        # sort; ``positions`` is simultaneously the inverse permutation
        # used to scatter the smoothed profile back at the end
        nv_run = jnp.cumsum(valid)
        nv = nv_run[-1]
        positions = jnp.where(valid, nv_run - 1, nv + idx - nv_run)
        order = jnp.zeros(n, dtype=idx.dtype).at[positions].set(idx)
        avg_c = jnp.where(valid[order], avg[order], 0.0)
        ea_c = ea[order]
        cmask_c = jnp.asarray(cmask)[order]
        in_c = idx < nv

        # ---- scipy savgol_filter(a, nsmooth, 1) on length-nv array -----
        h = nsmooth // 2
        kern = jnp.ones(nsmooth, dtype=avg.dtype) / nsmooth
        mov = jnp.convolve(avg_c, kern, mode="same")
        t = jnp.arange(nsmooth, dtype=avg.dtype)
        tm = (nsmooth - 1) / 2.0
        denom = jnp.sum((t - tm) ** 2)

        def linfit(seg):
            b = jnp.sum((t - tm) * seg) / denom
            a0 = jnp.mean(seg) - b * tm
            return a0, b

        a_h, b_h = linfit(jax.lax.dynamic_slice(avg_c, (0,), (nsmooth,)))
        start_t = jnp.maximum(nv - nsmooth, 0)
        a_t, b_t = linfit(jax.lax.dynamic_slice(avg_c, (start_t,),
                                                (nsmooth,)))
        filt_c = mov
        filt_c = jnp.where(idx < h, a_h + b_h * idx, filt_c)
        filt_c = jnp.where((idx >= nv - h) & in_c,
                           a_t + b_t * (idx - start_t), filt_c)
        filt_c = jnp.where(in_c, filt_c, jnp.nan)

        # ---- peak (dynspec.py:693-699; argmin over ALL compacted) ------
        search = in_c & cmask_c
        maxval = jnp.max(jnp.where(search, filt_c, -jnp.inf))
        peak_ind = jnp.argmin(jnp.where(in_c, jnp.abs(filt_c - maxval),
                                        jnp.inf))
        max_power = filt_c[peak_ind]

        nv_safe = jnp.maximum(nv, 1)

        def walk(threshold):
            """The reference _walk (dynspec.py:702-718) in closed form:
            terminal ind = smallest j >= 1 with [j == 1 and
            filt[peak] <= thr] or [j >= 2 and filt[(peak -/+ j) mod nv]
            <= thr] or [peak + j >= nv - 1] (BOTH directions guard on
            peak + j — the reference quirk)."""
            stop_guard = peak_ind + idx >= nv - 1
            first = (idx == 1) & (max_power <= threshold)

            def terminal(values):
                crossed = (idx >= 2) & (values <= threshold)
                cond = (idx >= 1) & (first | crossed | stop_guard)
                return jnp.min(jnp.where(cond, idx, n))

            v_l = filt_c[jnp.mod(peak_ind - idx, nv_safe)]
            v_r = filt_c[jnp.mod(peak_ind + idx, nv_safe)]
            return terminal(v_l), terminal(v_r)

        def window_mask(i1, i2):
            """numpy slice arr[peak-i1 : peak+i2] on the length-nv
            compacted array, including python's negative-start wrap
            (kept bit-for-bit, see _measure_peak).  Returns
            (mask, astart, stop) so callers share ONE wrap expression."""
            start = peak_ind - i1
            stop = peak_ind + i2
            astart = jnp.where(start < 0, nv + start, start)
            return in_c & (idx >= astart) & (idx < stop), astart, stop

        i1, _ = walk(max_power + low_power_diff)
        _, i2 = walk(max_power + high_power_diff)
        wmask, wstart, wstop = window_mask(i1, i2)
        w = wmask.astype(avg.dtype)
        if use_log:
            yfit, eta, etaerr_fit = fit_log_parabola(ea_c, avg_c, w=w,
                                                     xp=jnp)
        else:
            yfit, eta, etaerr_fit = _fitpar(ea_c, avg_c, w=w, xp=jnp)

        etaerr = etaerr_fit
        if noise_error:
            j1, j2 = walk(max_power - noise)
            wn_, _, _ = window_mask(j1, j2)
            lo_eta = jnp.min(jnp.where(wn_, ea_c, jnp.inf))
            hi_eta = jnp.max(jnp.where(wn_, ea_c, -jnp.inf))
            # empty (wrapped) noise window: the numpy path guards ptp of
            # an empty slice to NaN (arc_fit.py _measure_peak), finite
            # eta kept — match that, not a -inf from the inf fills
            etaerr = jnp.where(jnp.any(wn_), (hi_eta - lo_eta) / 2,
                               jnp.nan)

        # the reference's forward-parabola check, on the WINDOW slice
        # with index spacing exactly as numpy computes it
        # (mean(np.gradient(np.diff(yfit_window))) > 0, dynspec.py:598) —
        # computed on the full grid the sign can flip on the sqrt-spaced
        # eta axis and falsely reject fits the reference accepts
        wlen = wstop - wstart
        m = wlen - 1                       # diff length
        dfull = jnp.diff(yfit)             # [n-1]; pair (k, k+1)
        k = jnp.arange(n)
        safe = lambda i: jnp.clip(i, 0, n - 2)  # noqa: E731
        d0 = dfull[safe(wstart + k)]
        dm = dfull[safe(wstart + k - 1)]
        dp = dfull[safe(wstart + k + 1)]
        g = jnp.where(k == 0, dp - d0,
                      jnp.where(k == m - 1, d0 - dm, (dp - dm) / 2))
        g_mean = (jnp.sum(jnp.where(k < m, g, 0.0))
                  / jnp.maximum(m, 1))

        # degenerate lanes -> NaN (the numpy path RAISES for every one of
        # these and the batch driver quarantines NaN): profile shorter
        # than the smoother; no constraint point among the valid
        # entries; a parabola window of < 3 points (the reference's
        # np.gradient forward-check crashes there — and a 2-point
        # parabola's vertex is pure floating-point noise, which showed
        # up as plain-vs-sharded nondeterminism); or a forward
        # (upward-opening) fitted parabola, which the reference raises
        # on unconditionally (dynspec.py:598), not just for arms
        # flat-window degeneracy: windowed power constant to f.p. dust
        # makes the vertex rounding noise — numpy raises there (see
        # _measure_peak); the decision reads bit-identical profile
        # values, so the two backends always agree
        y_hi = jnp.max(jnp.where(wmask, avg_c, -jnp.inf))
        y_lo = jnp.min(jnp.where(wmask, avg_c, jnp.inf))
        flat = ((y_hi - y_lo)
                <= _FLAT_WINDOW_TOL * jnp.maximum(1.0, jnp.abs(y_hi)))
        bad = ((nv < nsmooth) | ~jnp.any(search)
               | (jnp.sum(w > 0) < 3) | (g_mean > 0) | flat)
        eta = jnp.where(bad, jnp.nan, eta)
        etaerr = jnp.where(bad, jnp.nan, etaerr)
        # the whole fit is absent on the numpy path (raise): etaerr2
        # from the degenerate normal equations must not leak either
        etaerr_fit = jnp.where(bad, jnp.nan, etaerr_fit)

        # full-grid profile outputs (NaN at invalid), matching the old
        # output contract: scatter the compacted smooth back
        inv = positions   # inverse of ``order`` by construction
        avg_f = jnp.where(valid, avg, jnp.nan)
        filt_full = jnp.where(valid, filt_c[inv], jnp.nan)
        return eta, etaerr, etaerr_fit, avg_f, filt_full

    return measure_profile_fast if arc_tail == "fast" else measure_profile


@functools.lru_cache(maxsize=None)
def make_profile_measurer(numsteps, nsmooth=5, low_power_diff=-3.0,
                          high_power_diff=-1.5, noise_error=True,
                          asymm=False, n_windows=None, arc_tail="exact"):
    """Axes-free norm_sspec measurement unit: fold the normalised
    delay-scrunched profile's arms onto the eta grid and run the
    measurement tail — with every grid-shaped static (the ascending eta
    array, the ``eta < emax`` validity window, the K constraint masks)
    entering PER CALL instead of being baked in at build time.

    This is the shape-stable back-end of the split pipeline
    (``PipelineConfig.split_programs``): the profile length is
    ``numsteps`` (config, not observing grid), so ONE compiled program
    serves every (nf, nt) — a novel dynspec shape recompiles only the
    shape-volatile front-end.  Called with numpy inputs (as the
    per-axes fitter does) the values bake into the caller's trace
    exactly as before; called with runtime arrays the program is
    grid-independent.

    Returns ``measure(prof [n], noise, eta_array [m], keep [m],
    cmasks [K, m]) -> measurement tuple`` (the contract
    :func:`pack_measurement` / the fitter-internal ``_pack`` consume).
    """
    import jax.numpy as jnp

    n = int(numsteps)
    measure_profile = _profile_tail(int(nsmooth), float(low_power_diff),
                                    float(high_power_diff),
                                    bool(noise_error), str(arc_tail))
    fdopnew = np.linspace(-1.0, 1.0, n)
    etafrac = np.linspace(-1.0, 1.0, n)
    ipos = np.where(etafrac > 1 / (2 * n))[0]
    ineg = np.where(etafrac < -1 / (2 * n))[0]
    # +2 dB quirk index (dynspec.py:864-866): static on the fdopnew grid
    i_at_1 = int(np.argmin(np.abs(fdopnew - 1) - 2))

    def measure(prof, noise, eta_array, keep, cmasks):
        prof = jnp.where(prof[i_at_1] < 0, prof + 2.0, prof)

        # ---- fold arms onto the eta grid -------------------------------
        def measure_arm(arm, cmask):
            # arm indexed like ipos (descending eta); flip to ascending
            avg = arm[::-1]
            valid = jnp.isfinite(avg) & jnp.asarray(keep)
            return measure_profile(avg, valid, noise,
                                   jnp.asarray(eta_array),
                                   jnp.asarray(cmask), use_log=False)

        right = prof[ipos]
        left = prof[ineg][::-1]
        combined = (right + left) / 2
        if n_windows is not None:
            per = [measure_arm(combined, cmasks[k])
                   for k in range(int(n_windows))]
            return (jnp.stack([q[0] for q in per]),
                    jnp.stack([q[1] for q in per]),
                    jnp.stack([q[2] for q in per]),
                    per[0][3], per[0][4], noise)
        out = measure_arm(combined, cmasks[0]) + (noise,)
        if asymm:
            el, eel = measure_arm(left, cmasks[0])[:2]
            er, eer = measure_arm(right, cmasks[0])[:2]
            out = out + (el, eel, er, eer)
        return out

    return measure


def pack_measurement(res, lamsteps, profile_eta, asymm=False):
    """Measurement tuple -> :class:`ArcFit` — the split back-end's
    counterpart of the fitter-internal ``_pack`` (same field contract,
    ``profile_eta`` supplied at runtime instead of baked)."""
    import jax.numpy as jnp

    eta, etaerr, etaerr2, avg, filt, noise = res[:6]
    arms = {}
    if asymm:
        arms = dict(zip(("eta_left", "etaerr_left", "eta_right",
                         "etaerr_right"), res[6:10]))
    return ArcFit(eta=eta, etaerr=etaerr, etaerr2=etaerr2,
                  lamsteps=lamsteps, profile_eta=jnp.asarray(profile_eta),
                  profile_power=avg, profile_power_filt=filt,
                  noise=noise, **arms)


@functools.lru_cache(maxsize=None)
def _make_arc_fitter_cached(fdop_key, yaxis_key, tdel_key, freq, lamsteps,
                            method, delmax, numsteps, startbin, cutmid,
                            etamax, etamin, low_power_diff, high_power_diff,
                            ref_freq, constraint, nsmooth, noise_error,
                            asymm=False, constraints=None,
                            scrunch_rows=0, arc_tail="exact"):
    if asymm and constraints is not None:
        raise ValueError("asymm=True and multi-arc constraints are "
                         "mutually exclusive on the batched fitter")
    import jax
    import jax.numpy as jnp

    fdop = np.frombuffer(fdop_key[0]).reshape(fdop_key[1])
    yaxis = np.frombuffer(yaxis_key[0]).reshape(yaxis_key[1])
    tdel_axis = np.frombuffer(tdel_key[0]).reshape(tdel_key[1])

    # ---- host-side static precomputation -------------------------------
    # One frequency adjustment for the fit-level delay cut (dynspec.py:428-
    # 429); norm_sspec then re-applies it internally (dynspec.py:796-797) —
    # the reference's double-adjustment quirk, reproduced for parity.
    # The row indices come from the shared rule so the driver's fused
    # sspec crop (norm_sspec_row_window) resolves identically.
    ind, ind_norm, dmax_raw = norm_sspec_row_window(
        tdel_axis, freq, ref_freq=ref_freq, delmax=delmax)
    dmax = dmax_raw * (ref_freq / freq) ** 2
    ymax = yaxis[ind] if lamsteps else dmax
    yc = yaxis[:ind]
    emax = etamax if etamax is not None else \
        ymax / ((fdop[1] - fdop[0]) * cutmid) ** 2
    emin = etamin if etamin is not None else \
        (yc[1] - yc[0]) * startbin / np.max(fdop) ** 2
    cons = np.asarray(constraint, dtype=np.float64)
    emin_norm = emin
    if not lamsteps:
        b2e = _beta_to_eta_factor(freq, ref_freq)
        emax = emax / (freq / ref_freq) ** 2 * b2e
        emin = emin / (freq / ref_freq) ** 2 * b2e
        cons = cons / (freq / ref_freq) ** 2 * b2e
        # norm_sspec converts the (already converted) eta again
        # (dynspec.py:820-825) — second half of the same quirk
        emin_norm = emin / (freq / ref_freq) ** 2 * b2e
    else:
        emin_norm = emin

    n = int(numsteps)
    # constraint sanity: the masks are host-side static, so an impossible
    # window fails at build time like the numpy path does at fit time
    # (otherwise the traced argmax would degenerate silently to index 0)
    def _check_constraint(grid_mask, grid, window=None):
        if not grid_mask.any():
            w = tuple(cons) if window is None else tuple(window)
            raise ValueError(
                f"no eta grid points inside constraint {w} "
                f"(grid spans {grid.min():.4g}..{grid.max():.4g})")

    # norm_sspec internals (maxnormfac=1): rows startbin..ind_norm-1
    tdel_rows = yaxis[startbin:ind_norm]
    scales = np.sqrt(tdel_rows / emin_norm)         # [R] per-row fdop scale
    fdopnew = np.linspace(-1.0, 1.0, n)
    # fold indices (static): positive/negative arms of fdopnew
    etafrac = np.linspace(-1.0, 1.0, n)
    ipos = np.where(etafrac > 1 / (2 * n))[0]
    ineg = np.where(etafrac < -1 / (2 * n))[0]
    etafrac_avg = 1.0 / etafrac[ipos]               # descending eta
    eta_array = emin * etafrac_avg[::-1] ** 2       # ascending in eta
    keep_static = eta_array < emax                  # static part of validity
    # multi-arc mode: one shared profile measured under K constraint
    # windows (constraints=...); single-arc mode uses the one constraint.
    # Windows get the same unit conversion the single constraint received
    # above (lamsteps=False fits run in converted beta-eta units)
    def _conv_window(c):
        c = np.asarray(c, dtype=np.float64)
        if not lamsteps:
            c = c / (freq / ref_freq) ** 2 * _beta_to_eta_factor(freq,
                                                                ref_freq)
        return c

    cons_windows = ([cons] if constraints is None
                    else [_conv_window(c) for c in constraints])
    cons_masks = [(eta_array > c[0]) & (eta_array < c[1])
                  for c in cons_windows]
    cons_mask = cons_masks[0]
    if method == "norm_sspec":
        # the searchable region is the constraint INTERSECTED with the
        # static validity window (eta < emax): a constraint lying wholly
        # past emax would degenerate silently at fit time otherwise
        for cm, w in zip(cons_masks, cons_windows):
            _check_constraint(cm & keep_static, eta_array[keep_static],
                              window=w)
    # cutmid NaN columns of the row-normalised spectrum (norm_sspec flavour:
    # floor on both sides, dynspec.py:838-839)
    ncol = len(fdop)
    if scrunch_rows == "pallas" and ncol >= 128 and ncol % 128:
        # Mosaic's gather decomposition works in 128-lane segments
        # (ops/resample_pallas.py); non-conforming Doppler widths (only
        # reachable via hand-cropped spectra passed straight to this
        # fitter — the pipeline's FFT-padded grids are always pow2, so
        # resolve_routes' recorded "pallas" stays truthful there)
        # demote to the scan route rather than erroring, and say so
        from ..utils.log import get_logger, log_event

        log_event(get_logger(), "arc_scrunch_demoted", route="scan",
                  block=64, ncol=ncol,
                  reason="ncol not tileable by 128-lane segments")
        scrunch_rows = 64
    cut_lo = int(ncol / 2 - np.floor(cutmid / 2))
    cut_hi = int(ncol / 2 + np.floor(cutmid / 2))
    col_nan = np.zeros(ncol, dtype=bool)
    col_nan[cut_lo:cut_hi] = True
    # fdop is a uniform grid (sspec_axes), so row interpolation reduces to
    # direct index arithmetic — no searchsorted (log-n gather chains) in
    # the hot vmapped row loop.  The grid MUST be uniform for this; fail
    # loudly for exotic callers.
    f0 = float(fdop[0])
    dfd = float(fdop[1] - fdop[0])
    if not np.allclose(np.diff(fdop), dfd, rtol=1e-9, atol=0.0):
        raise ValueError("jax arc fitter requires a uniform fdop grid "
                         "(sspec_axes produces one); use backend='numpy' "
                         "for non-uniform axes")
    # half-ulp slack so ceil/floor match searchsorted when a query lands
    # exactly on a grid value (linspace grids differ in the last ulp)
    _EDGE_EPS = 1e-12

    def _stack_windows(measure_fn, masks, noise):
        """Measure one shared profile under K constraint windows and
        stack the per-window (eta, etaerr, etaerr2); profile/filter come
        from the first window (identical across windows)."""
        per = [measure_fn(cmask=cm) for cm in masks]
        return (jnp.stack([q[0] for q in per]),
                jnp.stack([q[1] for q in per]),
                jnp.stack([q[2] for q in per]),
                per[0][3], per[0][4], noise)

    # ---- static row-interp pattern ------------------------------------
    # The interpolation positions depend only on the (fdop, scales) grids,
    # never on the data: precompute the [R, n] gather indices and weights
    # host-side once, so the device step is one take_along_axis + fused
    # multiply-adds instead of per-row index arithmetic.
    def _row_interp_pattern():
        s = scales[:, None]                                  # [R, 1]
        blo = (-s - f0) / dfd
        bhi = (s - f0) / dfd
        lo = np.clip(np.ceil(blo - _EDGE_EPS * np.abs(blo)).astype(np.int64),
                     0, ncol - 1)
        hi = np.clip(np.floor(bhi + _EDGE_EPS * np.abs(bhi)).astype(np.int64),
                     0, ncol - 1)
        q = np.clip(fdopnew[None, :] * s, f0 + lo * dfd, f0 + hi * dfd)
        pos = np.clip((q - f0) / dfd, 0.0, ncol - 1.0)
        i0 = np.clip(np.floor(pos).astype(np.int64), 0, ncol - 2)
        w = pos - i0
        return i0.astype(np.int32), w

    _i0_static, _w_static = _row_interp_pattern()            # [R, n]

    def profile_of(sspec):
        """Per-epoch half: noise estimate + normalised delay-scrunched
        profile [n].  Split from the measurement tail so the stacked
        mode can nanmean profiles across epochs (a batch-axis reduction
        — psum under a data-sharded mesh) before ONE measurement."""
        # ---- noise estimate (dynspec.py:446-451,463) -------------------
        noise = _noise_estimate(sspec, cutmid, xp=jnp)
        noise = noise / (ind - startbin)

        # ---- normalised, delay-scrunched profile -----------------------
        rows = sspec[startbin:ind_norm, :]
        rows = jnp.where(col_nan[None, :], jnp.nan, rows)

        if scrunch_rows == "pallas":
            # Fused Pallas kernel: gather + lerp + NaN-masked accumulate
            # entirely in VMEM — measured 3.5x the scan path on-chip at
            # the bench shape (benchmarks/pallas_ab.py, round-4 verdict
            # "wire").  Off-TPU executions (CPU-fallback bench, forced
            # route in CI) run the same kernel in interpret mode.
            from ..ops.resample_pallas import row_scrunch_pallas

            prof = row_scrunch_pallas(rows, _i0_static, _w_static,
                                      interpret="auto")
        elif scrunch_rows:
            # lax.scan over row blocks: the full-gather path materialises
            # [R, n] (x3 under a B-epoch vmap: [B, R, n] v0/v1/norm in
            # HBM); accumulating the delay-scrunch nansum/count per block
            # caps the working set at [B, scrunch_rows, n] regardless of
            # the delay cut.  Shared with the Pallas A/B baseline
            # (ops.resample_pallas.row_scrunch_scan), so the
            # prove-or-remove measurement always races the kernel
            # against exactly this production path.
            from ..ops.resample_pallas import row_scrunch_scan

            prof = row_scrunch_scan(rows, _i0_static, _w_static,
                                    block_r=scrunch_rows)
        else:
            i0 = jnp.asarray(_i0_static)
            w = jnp.asarray(_w_static, dtype=rows.dtype)
            # mode="clip": i0 is host-clamped to [0, ncol-2]; the
            # default fill mode's bounds masks cost seconds of XLA
            # constant folding over the [R, n] index constants
            v0 = jnp.take_along_axis(rows, i0, axis=1, mode="clip")
            v1 = jnp.take_along_axis(rows, i0 + 1, axis=1, mode="clip")
            norm = v0 * (1.0 - w) + v1 * w                   # [R, n]
            prof = jnp.nanmean(norm, axis=0)                 # [n]
        return prof, noise

    # measurement tail + arm fold: ONE axes-free implementation
    # (module-level factories, shared with the split pipeline's
    # shape-stable back-end unit).  Called here with the baked numpy
    # statics, the values embed into the trace exactly as the old
    # closures did (bit-identical programs).
    measure_profile = _profile_tail(nsmooth, low_power_diff,
                                    high_power_diff, noise_error,
                                    arc_tail)
    _measurer = make_profile_measurer(
        n, nsmooth=nsmooth, low_power_diff=low_power_diff,
        high_power_diff=high_power_diff, noise_error=noise_error,
        asymm=asymm,
        n_windows=None if constraints is None else len(cons_masks),
        arc_tail=arc_tail)

    def measure_from_prof(prof, noise):
        """Measurement tail on a (possibly epoch-stacked) profile."""
        return _measurer(prof, noise, eta_array, keep_static,
                         tuple(cons_masks))

    def one_epoch(sspec):
        prof, noise = profile_of(sspec)
        return measure_from_prof(prof, noise)

    # ---- gridmax statics (dynspec.py:516-659) --------------------------
    if method == "gridmax":
        nrow_g = ind  # delay rows kept
        eta_array_g = np.linspace(np.sqrt(emin), np.sqrt(emax),
                                  int(numsteps)) ** 2
        cons_masks_g = [(eta_array_g > c[0]) & (eta_array_g < c[1])
                        for c in cons_windows]
        cons_mask_g = cons_masks_g[0]
        for cm, w in zip(cons_masks_g, cons_windows):
            _check_constraint(cm, eta_array_g, window=w)
        # fit-level cutmid mask: floor/CEIL (dynspec.py:455-457) — one
        # column wider on the high side than norm_sspec's floor/floor mask
        col_nan_g = np.zeros(ncol, dtype=bool)
        col_nan_g[int(ncol / 2 - np.floor(cutmid / 2)):
                  int(ncol / 2 + np.ceil(cutmid / 2))] = True
        x_f = fdop
        # reference pixel mapping: column positions are STATIC
        # (dynspec.py:540: scaled by shape, not shape-1 — quirk kept)
        xpx = (x_f - x_f.min()) / (x_f.max() - x_f.min()) * ncol
        col_ok = (xpx >= 0) & (xpx <= ncol - 1)     # cval=nan analogue
        jx0 = np.clip(np.floor(xpx).astype(np.int32), 0, ncol - 2)
        wx = (xpx - jx0).astype(np.float64)
        xmin2 = float(np.min(x_f ** 2))
        ymax_g = float(yc.max())
        side_l = x_f < 0
        side_r = x_f > 0
        chunk = 256  # [chunk, ncol] sampling slabs bound device memory

        def one_epoch_gridmax(sspec):
            noise = _noise_estimate(sspec, cutmid, xp=jnp)
            noise = noise / (ind - startbin)

            z = sspec[:ind, :]
            z = jnp.where(col_nan_g[None, :], jnp.nan, z)
            z = z.at[:startbin, :].set(jnp.nan)

            x2 = jnp.asarray(x_f ** 2)
            jx0_j = jnp.asarray(jx0)
            wx_j = jnp.asarray(wx)

            def sample_eta(ieta):
                ynew = ieta * x2
                ymin = ieta * xmin2
                ynewpx = (ynew - ymin) / (ymax_g - ymin) * nrow_g
                row_ok = (ynewpx >= 0) & (ynewpx <= nrow_g - 1)
                iy0 = jnp.clip(jnp.floor(ynewpx).astype(jnp.int32), 0,
                               nrow_g - 2)
                wy = ynewpx - iy0
                v = (z[iy0, jx0_j] * (1 - wy) * (1 - wx_j)
                     + z[iy0 + 1, jx0_j] * wy * (1 - wx_j)
                     + z[iy0, jx0_j + 1] * (1 - wy) * wx_j
                     + z[iy0 + 1, jx0_j + 1] * wy * wx_j)
                v = jnp.where(row_ok & jnp.asarray(col_ok), v, jnp.nan)
                inarc = ynew < ymax_g

                def side_mean(side):
                    ok = jnp.isfinite(v) & inarc & jnp.asarray(side)
                    s = jnp.sum(jnp.where(ok, v, 0.0))
                    c = jnp.sum(ok)
                    return jnp.where(c > 0, s / jnp.maximum(c, 1), jnp.nan)

                sl, sr = side_mean(side_l), side_mean(side_r)
                return jnp.stack([(sl + sr) / 2, sl, sr])

            # chunked over the eta grid: [chunk, ncol] slabs, not [S, ncol]
            S = len(eta_array_g)
            pad = (-S) % chunk
            eta_p = jnp.asarray(np.pad(eta_array_g, (0, pad),
                                       constant_values=1.0))
            pows = jax.lax.map(jax.vmap(sample_eta),
                               eta_p.reshape(-1, chunk)
                               ).reshape(-1, 3)[:S]

            def measure_pow(p, cmask=None):
                return measure_profile(p, jnp.isfinite(p), noise,
                                       jnp.asarray(eta_array_g),
                                       cons_mask_g if cmask is None
                                       else cmask, use_log=True)

            if constraints is not None:
                return _stack_windows(
                    functools.partial(measure_pow, pows[:, 0]),
                    cons_masks_g, noise)
            out = measure_pow(pows[:, 0]) + (noise,)
            if asymm:
                el, eel = measure_pow(pows[:, 1])[:2]
                er, eer = measure_pow(pows[:, 2])[:2]
                out = out + (el, eel, er, eer)
            return out

        epoch_fn = one_epoch_gridmax
        profile_eta_out = eta_array_g
    else:
        epoch_fn = one_epoch
        profile_eta_out = eta_array

    def _pack(res):
        """Measurement tuple -> ArcFit (shared by the batched and
        stacked jit bodies so their result shapes cannot drift)."""
        eta, etaerr, etaerr2, avg, filt, noise = res[:6]
        arms = {}
        if asymm:
            arms = dict(zip(("eta_left", "etaerr_left", "eta_right",
                             "etaerr_right"), res[6:10]))
        return ArcFit(eta=eta, etaerr=etaerr, etaerr2=etaerr2,
                      lamsteps=lamsteps,
                      profile_eta=jnp.asarray(profile_eta_out),
                      profile_power=avg, profile_power_filt=filt,
                      noise=noise, **arms)

    @jax.jit
    def impl(sspec_batch):
        return _pack(jax.vmap(epoch_fn)(sspec_batch))

    if method == "norm_sspec":
        # Epoch-stacked mode (BEYOND the reference, which fits one file
        # at a time): nanmean the per-epoch normalised profiles across
        # the batch — incoherent averaging that grows weak-arc S/N as
        # sqrt(B) (standard campaign practice the serial reference
        # cannot express) — then run ONE measurement.  TPU-native: the
        # stack is a batch-axis reduction (psum under a data-sharded
        # mesh), so a campaign's worth of epochs fits in one jit.  The
        # walk noise level scales as mean(noise)/sqrt(B) to match the
        # stacked profile's variance.
        @jax.jit
        def impl_stacked(sspec_batch):
            profs, noises = jax.vmap(profile_of)(sspec_batch)
            prof = jnp.nanmean(profs, axis=0)
            # nan-robust like the profile stack: one corrupted epoch
            # (NaN outer-quadrant noise region -> _noise_estimate NaN)
            # must not poison the campaign; sqrt of the FINITE count
            # matches the variance of the epochs that contributed
            n_ok = jnp.maximum(jnp.sum(jnp.isfinite(noises)), 1)
            noise = (jnp.nanmean(noises)
                     / jnp.sqrt(n_ok.astype(prof.dtype)))
            return _pack(measure_from_prof(prof, noise))

        impl.stacked = impl_stacked
        # split-pipeline export (PipelineConfig.split_programs): the
        # shape-volatile per-epoch half (profile extraction) and the
        # grid inputs the axes-free measurer unit consumes at runtime
        # (its program identity is driver.split_backend_desc).  The
        # unsplit path above bakes exactly these values into its
        # trace, so split/unsplit fits are bit-identical (tier-1
        # asserts the CSV bytes).
        impl.profile_of = profile_of
        impl.measure_inputs = {
            "arc_eta": np.asarray(eta_array, dtype=np.float64),
            "arc_keep": np.asarray(keep_static, dtype=bool),
            "arc_cmasks": np.stack([np.asarray(m, dtype=bool)
                                    for m in cons_masks]),
        }

    return impl


def make_arc_fitter(fdop, yaxis, tdel, freq, lamsteps=True,
                    method="norm_sspec", delmax=None, numsteps=1024,
                    startbin=3, cutmid=3, etamax=None, etamin=None,
                    low_power_diff=-3.0, high_power_diff=-1.5,
                    ref_freq=1400.0, constraint=(0, np.inf), nsmooth=5,
                    noise_error=True, asymm=False, constraints=None,
                    scrunch_rows=0, arc_tail="exact"):
    """Build a jit'd batched arc fitter for a fixed (fdop, yaxis) grid.

    Returns ``fitter(sspec_batch [B, nr, nc]) -> ArcFit`` of [B] arrays.
    For ``method="norm_sspec"`` the returned fitter also exposes
    ``fitter.stacked(sspec_batch) -> ArcFit`` of scalars: the per-epoch
    normalised profiles are nanmean-stacked across the batch before ONE
    measurement — incoherent campaign averaging (weak-arc S/N grows as
    sqrt(B)) that the reference's one-file-at-a-time fitter cannot
    express; the stack is a batch-axis reduction, so it shards over a
    data-parallel mesh unchanged.
    All grid-dependent decisions (delay cut, eta grid, fold indices) are
    made host-side once; the per-epoch measurement is pure fixed-shape jax.
    Both reference methods are implemented: ``norm_sspec`` (row
    normalisation) and ``gridmax`` (chunked bilinear sampling along
    ``tdel = eta fdop^2`` trial arcs).

    ``scrunch_rows`` (norm_sspec only): 0 materialises the full [R, n]
    row-resample ([B, R, n] under a batch); a positive value accumulates
    the delay-scrunch over lax.scan blocks of that many rows, trading
    one big gather for bounded HBM working set — same values modulo
    floating-point association; ``"pallas"`` routes to the fused VMEM
    kernel (ops/resample_pallas, measured 3.5x the scan on-chip;
    interpret mode off-TPU, scan fallback for non-conforming Doppler
    widths).

    ``arc_tail``: ``"exact"`` (default) runs the measurement tail with
    the reference's compacted-array semantics bit-for-bit
    (dynspec.py:580-618,702-744 — the parity contract); ``"fast"`` runs
    the same stages as masked reductions on the full grid (no
    compaction scatter/gathers, no savgol edge linfits, no mod-wrap
    walks).  Opt-in speed knob: eta agrees with the exact tail to
    within the fit's own etaerr on healthy arcs, NOT bit-exactly.
    """
    if method not in ("norm_sspec", "gridmax"):
        raise ValueError(f"unknown arc fitting method {method!r}")
    if arc_tail not in ("exact", "fast"):
        raise ValueError(f"arc_tail must be 'exact' or 'fast', got "
                         f"{arc_tail!r}")
    if scrunch_rows != "pallas" and (isinstance(scrunch_rows, str)
                                     or int(scrunch_rows) < 0):
        raise ValueError(f"scrunch_rows must be >= 0 or 'pallas', got "
                         f"{scrunch_rows!r}")
    fdop = np.ascontiguousarray(np.asarray(fdop, dtype=np.float64))
    yaxis = np.ascontiguousarray(np.asarray(yaxis, dtype=np.float64))
    tdel = np.ascontiguousarray(np.asarray(tdel, dtype=np.float64))
    key = lambda a: (a.tobytes(), a.shape)  # noqa: E731
    return _make_arc_fitter_cached(
        key(fdop), key(yaxis), key(tdel), float(freq), bool(lamsteps),
        method, None if delmax is None else float(delmax), int(numsteps),
        int(startbin), int(cutmid),
        None if etamax is None else float(etamax),
        None if etamin is None else float(etamin), float(low_power_diff),
        float(high_power_diff), float(ref_freq),
        (float(constraint[0]), float(constraint[1])), int(nsmooth),
        bool(noise_error), bool(asymm),
        None if constraints is None else tuple(
            (float(lo), float(hi)) for lo, hi in constraints),
        scrunch_rows if scrunch_rows == "pallas" else int(scrunch_rows),
        str(arc_tail))


def fit_arcs_multi(sec: SecSpec, freq: float, brackets,
                   method: str = "norm_sspec", backend: str = "numpy",
                   low_power_diff: float = -3.0,
                   high_power_diff: float = -1.5,
                   noise_error: bool = True, **kw) -> list[ArcFit]:
    """Measure several arcs in one secondary spectrum (the reference's
    multi-arc mode: etamin/etamax arrays segment the sqrt-eta grid,
    dynspec.py:470-491).

    ``brackets`` is a sequence of (eta_lo, eta_hi) curvature windows (same
    units as the fit: beta-eta for lamsteps spectra; ``None`` bounds mean
    open-ended).  The global power-vs-curvature profile is computed ONCE,
    then each arc is measured with the peak search constrained to its
    window, as in the reference where one eta grid serves all arcs.
    Returns one ArcFit per bracket.
    """
    brackets = [(0.0 if lo is None else float(lo),
                 np.inf if hi is None else float(hi))
                for lo, hi in brackets]
    if method == "thetatheta":
        # each arc is its own bounded eigen-sweep: no shared profile to
        # reuse, and the bracket must be finite
        for lo, hi in brackets:
            if not (np.isfinite(lo) and np.isfinite(hi) and lo > 0):
                raise ValueError("thetatheta multi-arc brackets must be "
                                 "finite positive (lo, hi) windows")
        return [fit_arc(sec, freq, method=method, backend=backend,
                        etamin=lo, etamax=hi,
                        low_power_diff=low_power_diff,
                        high_power_diff=high_power_diff,
                        noise_error=noise_error, **kw)
                for lo, hi in brackets]
    # one full-profile fit (first bracket as the constraint just to get a
    # valid measurement); its profile/filter/noise are then re-measured
    # per window without recomputing the expensive normalisation
    first = fit_arc(sec, freq, method=method, backend=backend,
                    constraint=brackets[0],
                    low_power_diff=low_power_diff,
                    high_power_diff=high_power_diff,
                    noise_error=noise_error, **kw)
    fits = [first]
    eta_array = np.asarray(first.profile_eta)
    power = np.asarray(first.profile_power)
    filt = np.asarray(first.profile_power_filt)
    noise = float(np.asarray(first.noise))
    # profile_eta lives in converted (beta-eta) units for non-lamsteps
    # spectra (fit_arc converts internally, arc_fit.py:244-247): apply the
    # same conversion to the remaining brackets so all arcs are windowed
    # in consistent units
    ref_freq = kw.get("ref_freq", 1400.0)
    conv = 1.0 if sec.lamsteps else \
        _beta_to_eta_factor(freq, ref_freq) / (freq / ref_freq) ** 2
    for lo, hi in brackets[1:]:
        fits.append(_measure_peak(
            eta_array, power, filt, noise, (lo * conv, hi * conv),
            low_power_diff, high_power_diff, noise_error, sec.lamsteps,
            log_fit=(method == "gridmax")))
    return fits
