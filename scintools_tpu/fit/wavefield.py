"""Wavefield retrieval: recover the complex scattered E-field from a
dynamic spectrum via chunked theta-theta eigendecomposition.

A beyond-reference capability (the reference measures only power-domain
quantities).  The dynamic spectrum is an intensity ``I = |E|^2``; its
conjugate spectrum ``C = FFT2(I)`` is the autocorrelation of the conjugate
wavefield, so interference between scattered images at Doppler angles
``theta1, theta2`` (fd units) puts

    C(fd = theta1 - theta2, tau = eta*(theta1^2 - theta2^2))
        ~ mu(theta1) * conj(mu(theta2))

i.e. the COMPLEX theta-theta matrix sampled at the true curvature is
approximately rank-1 Hermitian, and its principal eigenvector is the
complex image amplitude ``mu(theta)`` — phases included — up to one
global phase (Sprenger et al. 2021; Baker et al. 2022 "interstellar
holography").

A single global eigenvector over the whole spectrum does NOT work: the
stationary-phase mapping only holds locally (curvature drifts with
frequency as eta ~ 1/f^2, and off-grid bin leakage scrambles the phases
— measured in round 1, dynspec correlation ~ 0).  The published remedy,
implemented here, is to *chunk* the dynspec into overlapping Hann-
windowed time-frequency blocks, retrieve ``mu`` per chunk (with eta
rescaled to the chunk centre frequency), reconstruct each chunk's field
from its own image model, and stitch the chunks by overlap-add — fixing
each chunk's unknown global phase against the already-accumulated field
in the 50%-overlap region.

Everything device-side is fixed-shape: chunks share one [nf_c, nt_c]
geometry, so the jax path retrieves ALL chunks in one vmapped jit
(batched exact NUDFT matmuls -> fixed-step power iteration -> two
reconstruction matmuls); only the (cheap, sequential) phase stitching
runs on host.

Validity: the fd/tau axes follow calc_sspec conventions (mHz, us —
``ops.sspec.sspec_axes``), so ``eta`` is the curvature ``fit_arc``
reports for a non-lamsteps spectrum, quoted at ``data.freq``.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from ..backend import resolve
from ..data import DynspecData

__all__ = ["Wavefield", "retrieve_wavefield",
           "retrieve_wavefield_batch", "intensity_corr",
           "auto_refine_decision"]

# Auto regime rule for the global arc-support refinement (round-4,
# verdict item 8).  The 12-regime ground-truth map
# (docs/wavefield.md, scripts/wavefield_regime_map.py) shows the global
# refinement lifts true-field fidelity everywhere EXCEPT where the
# chunked retrieval already explains the intensity well — the
# strong-screen signature.  The measurable discriminant is the
# intensity correlation of the stitched |E|^2 with the data: lifting
# regimes sit at corr 0.45-0.75, the two regressing cells at 0.81/0.94.
# Threshold 0.80 picks the better (or equal) branch in all 12 cells:
# corr < 0.80 -> refine (10 lifts), corr >= 0.80 -> skip (avoids
# 0.744->0.630 and keeps the flat 0.802 cell at its better value).
AUTO_REFINE_CORR_THRESHOLD = 0.80
AUTO_REFINE_ITERS = 30


def intensity_corr(field, dyn) -> float:
    """Pearson correlation of |field|^2 with the dynspec — the measured
    strong-screen discriminant used by the auto refinement rule (and a
    general retrieval-quality diagnostic; gauge-invariant by
    construction)."""
    field = np.asarray(field)
    dyn = np.asarray(dyn, dtype=np.float64)
    m = np.abs(field.ravel()) ** 2
    d = dyn.ravel()
    sd, sm = np.std(d), np.std(m)
    if sd == 0 or sm == 0 or not (np.isfinite(sd) and np.isfinite(sm)):
        # degenerate (constant / non-finite) input: no meaningful corr
        return float("nan")
    return float(np.corrcoef(d, m)[0, 1])


def auto_refine_decision(corr: float) -> bool:
    """True -> run the global refinement (weak/moderate regime).  A
    non-finite corr (degenerate input) SKIPS refinement: the GS pass
    would only spread the degeneracy through the whole field."""
    return bool(np.isfinite(corr) and corr < AUTO_REFINE_CORR_THRESHOLD)


@dataclasses.dataclass(frozen=True)
class Wavefield:
    """Retrieved complex wavefield + per-chunk diagnostics.

    ``field`` [nchan, nsub] is normalised so ``|field|^2`` is in the
    dynspec's flux units.  ``conc`` is each chunk's top-eigenmode energy
    fraction (1 = perfectly rank-1 theta-theta matrix); ``align`` is the
    phase-stitch quality in [0, 1] (normalised overlap inner product);
    chunks with no usable overlap to align against — the first chunk,
    and chunks stitched onto a dead/zero-power region — report NaN.
    """

    field: np.ndarray
    freqs: np.ndarray
    times: np.ndarray
    eta: float
    chunk_shape: tuple
    conc: np.ndarray
    align: np.ndarray
    theta: np.ndarray = None       # shared theta grid (fd units, mHz)
    chunk_etas: np.ndarray = None  # per-chunk curvature (us/mHz^2)
    refined_global: int = 0        # global-GS iterations actually applied
    # (0 = skipped; set by the "auto" rule or an explicit request)

    @property
    def model_dynspec(self) -> np.ndarray:
        """|E|^2 — compare against the input dynamic spectrum."""
        return np.abs(self.field) ** 2

    def save(self, path: str) -> None:
        """Persist to an .npz (complex field + axes + diagnostics).
        None-valued optional fields are omitted (a pickled None would
        make the file unloadable under np.load's allow_pickle=False)."""
        arrays = dict(field=self.field, freqs=self.freqs,
                      times=self.times, eta=self.eta,
                      chunk_shape=np.asarray(self.chunk_shape),
                      conc=self.conc, align=self.align,
                      refined_global=np.asarray(self.refined_global))
        if self.theta is not None:
            arrays["theta"] = self.theta
        if self.chunk_etas is not None:
            arrays["chunk_etas"] = self.chunk_etas
        np.savez_compressed(path, **arrays)

    @classmethod
    def load(cls, path: str) -> "Wavefield":
        with np.load(path) as z:
            return cls(field=z["field"], freqs=z["freqs"],
                       times=z["times"], eta=float(z["eta"]),
                       chunk_shape=tuple(int(x) for x in z["chunk_shape"]),
                       conc=z["conc"], align=z["align"],
                       theta=z["theta"] if "theta" in z.files else None,
                       chunk_etas=z["chunk_etas"]
                       if "chunk_etas" in z.files else None,
                       refined_global=int(z["refined_global"])
                       if "refined_global" in z.files else 0)

    def secspec(self, pad: int = 2, db: bool = True) -> "SecSpec":
        """Secondary spectrum of the FIELD: |FFT2(E)|^2.

        Unlike the intensity secondary spectrum (whose power fills the
        whole pairwise-difference manifold inside the arc), the field's
        spectrum puts power AT the scattered images themselves — on the
        single parabola tau = eta*fd^2 — so arcs are far sharper and
        individual images separable.  The delay axis is full-signed
        (the field is complex; no Hermitian fold), in calc_sspec units
        (fdop mHz, tdel us).  ``pad`` zero-pads each axis by that factor
        for finer spectral sampling.
        """
        from ..data import SecSpec

        E = np.asarray(self.field)
        nf, nt = E.shape
        dt_s = float(self.times[1] - self.times[0])
        df_mhz = float(abs(self.freqs[1] - self.freqs[0]))
        S = np.fft.fftshift(np.fft.fft2(E, s=(pad * nf, pad * nt)))
        P = np.abs(S) ** 2
        if db:
            with np.errstate(divide="ignore"):
                P = 10.0 * np.log10(P)
        fdop = np.fft.fftshift(np.fft.fftfreq(pad * nt, d=dt_s)) * 1e3
        tdel = np.fft.fftshift(np.fft.fftfreq(pad * nf, d=df_mhz))
        return SecSpec(sspec=P, fdop=fdop, tdel=tdel, lamsteps=False)


def _chunk_starts(n: int, size: int) -> list:
    """Start indices covering [0, n) with ~50% overlap; final chunk is
    clamped so the spectrum edge is always covered."""
    if size >= n:
        return [0]
    step = max(1, size // 2)
    starts = list(range(0, n - size + 1, step))
    if starts[-1] != n - size:
        starts.append(n - size)
    return starts


def _chunk_field_xp(chunk, w2d, eta_c, theta_max, geom, ntheta, niter,
                    mask_fd, mask_tau, xp, scan=None, cache=None,
                    refine=0):
    """Retrieve one chunk's complex field model.

    ``geom`` = (dt_s, df_mhz) — static python floats shared by every
    chunk.  ``eta_c``/``theta_max`` may be traced scalars.  Returns
    (E [nf_c, nt_c] complex, conc).

    The theta-theta matrix is sampled EXACTLY by a two-stage NUDFT
    rather than interpolating an FFT grid: theta differences take only
    2*ntheta-1 distinct Doppler values, so stage 1 is one [nf_c, nt_c] x
    [nt_c, 2*ntheta-1] complex matmul (the time-axis NUDFT at every
    distinct fd), and stage 2 evaluates the delay-axis NUDFT at each
    entry's tau = eta*(theta1^2-theta2^2) by a phase-weighted reduction
    over frequency.  Off-grid bilinear leakage was the dominant error of
    the FFT-grid variant (oracle-stitch fidelity 0.72 -> 0.82 on the
    synthetic-arc ground truth); both stages are matmul/reduce shaped,
    which is also the right form for the MXU.
    """
    dt_s, df_mhz = geom
    nf_c, nt_c = chunk.shape

    def memo(key, fn):
        # chunk-invariant tensors: the numpy host loop passes a dict so
        # grid phases are built once (keyed by eta_c where they depend
        # on it); the traced jax path passes None
        if cache is None:
            return fn()
        if key not in cache:
            cache[key] = fn()
        return cache[key]

    I = w2d * (chunk - xp.mean(chunk))
    t_loc = xp.arange(nt_c) * dt_s
    f_loc = xp.arange(nf_c) * df_mhz

    # theta grid (fd units, mHz); spacing d_th
    th = xp.linspace(-theta_max, theta_max, ntheta)
    d_th = th[1] - th[0]

    # stage 1: time-axis NUDFT at the distinct fd differences k*d_th
    ks = xp.arange(-(ntheta - 1), ntheta)
    P_t = memo("P_t", lambda: xp.exp(
        -2j * np.pi * (ks[:, None] * d_th * 1e-3)
        * t_loc[None, :]))                               # [2n-1, nt_c]
    B = I @ P_t.T                                        # [nf_c, 2n-1]

    # stage 2: delay-axis NUDFT at tau_ij = eta*(th_i^2 - th_j^2)
    t1, t2 = th[:, None], th[None, :]
    fd = t1 - t2
    tau = eta_c * (t1 ** 2 - t2 ** 2)
    kij = memo("kij", lambda: xp.round(fd / d_th).astype(xp.int32)
               + (ntheta - 1))

    def _stage2_phases():
        # mask (a) the spectral origin — it maps onto the theta1=theta2
        # diagonal at EVERY eta (C(0,0) would fill the diagonal with the
        # total power and swamp the rank-1 structure) — and (b) pairs
        # whose (fd, tau) fall outside the data's Nyquist window: theta
        # differences reach 2*theta_max in fd, and low-frequency chunks
        # carry eta_c above the shared span's design eta, so
        # out-of-window NUDFT samples would alias wrapped power
        fd_nyq = 1e3 / (2 * dt_s)
        tau_nyq = 1.0 / (2 * df_mhz)
        ph = xp.exp(-2j * np.pi * tau[None, :, :] * f_loc[:, None, None])
        origin = (xp.abs(fd) <= mask_fd) & (xp.abs(tau) <= mask_tau)
        dead = origin | (xp.abs(fd) > fd_nyq) | (xp.abs(tau) > tau_nyq)
        return ph, dead

    ph, dead = memo(("eta", float(eta_c)) if cache is not None else None,
                    _stage2_phases)
    TT = xp.sum(B[:, kij] * ph, axis=0)                  # [n, n]
    TT = xp.where(dead, 0.0, TT)
    H = 0.5 * (TT + xp.conj(TT.T))

    # principal eigenvector by fixed-step power iteration (identical on
    # both backends; H is Hermitian with a dominant positive eigenvalue).
    # The init is derived from H (zeros_like + 1 == ones) so that under
    # shard_map the scan carry carries H's varying-axis type — a literal
    # ones() is "unvarying" and newer jax rejects the carry mismatch
    v = (xp.zeros_like(H[0]) + 1.0) / np.sqrt(ntheta)
    if scan is None:
        for _ in range(niter):
            v = H @ v
            v = v / xp.maximum(xp.sqrt(xp.sum(xp.abs(v) ** 2)), 1e-30)
    else:
        def body(v, _):
            v = H @ v
            return v / xp.maximum(xp.sqrt(xp.sum(xp.abs(v) ** 2)),
                                  1e-30), None
        v, _ = scan(body, v, None, length=niter)
    lam = xp.real(xp.vdot(v, H @ v))
    tot = xp.maximum(xp.sum(xp.abs(H) ** 2), 1e-30)
    conc = lam ** 2 / tot
    mu = xp.sqrt(xp.maximum(lam, 0.0)) * v

    # forward model on the chunk footprint (chunk-local coordinates; the
    # per-theta phase offsets of absolute coordinates live in mu):
    #   E[f, t] = sum_j mu_j e^{2 pi i (tau_j * f_MHz + fd_j * 1e-3 * t_s)}
    ph_f = memo(("ph_f", float(eta_c)) if cache is not None else None,
                lambda: xp.exp(2j * np.pi * f_loc[:, None]
                               * (eta_c * th ** 2)[None, :]))
    ph_t = memo("ph_t", lambda: xp.exp(
        2j * np.pi * (th * 1e-3)[:, None] * t_loc[None, :]))
    E = (ph_f * mu[None, :]) @ ph_t

    # anchor the amplitude: window-weighted model power == window-weighted
    # chunk flux (the eigen-scale carries FFT/leakage factors)
    flux = xp.sum(w2d * xp.maximum(chunk, 0.0))
    model = xp.sum(w2d * xp.abs(E) ** 2)
    E = E * xp.sqrt(xp.maximum(flux, 0.0) / xp.maximum(model, 1e-30))

    if refine:
        # Fixed-count alternating projections (Gerchberg–Saxton style),
        # seeded by the eigenvector solution: (a) magnitude projection —
        # keep the model's phases but take |E| from the measured
        # intensity; (b) model projection — weighted least-squares of
        # that field back onto the theta-image basis (so the support
        # stays on the arc).  The rank-1 eigen step uses only the
        # theta-theta matrix's principal mode; the projection loop lets
        # ALL theta amplitudes adjust jointly to the measured
        # magnitudes, which is where weakly scattered (poorly rank-1)
        # chunks leave signal on the table.  The basis factorises
        # (A[(f,t),j] = ph_f[f,j] ph_t[j,t]) and the Hann weight is
        # separable, so the normal matrix is a Hadamard product of two
        # [ntheta, ntheta] Gram matrices — matmul/solve shaped, no
        # [nf, ntheta, ntheta] intermediate.
        wfv = xp.asarray(np.hanning(nf_c))
        wtv = xp.asarray(np.hanning(nt_c))
        Gt = memo("Gt_refine",
                  lambda: (xp.conj(ph_t) * wtv[None, :]) @ ph_t.T)
        Gf = memo(("Gf_refine", float(eta_c)) if cache is not None
                  else None,
                  lambda: (xp.conj(ph_f) * wfv[:, None]).T @ ph_f)
        G = Gf * Gt
        # the theta basis is overcomplete on a small chunk (G is
        # numerically SINGULAR: near-duplicate columns once the theta
        # spacing drops below the chunk's Doppler resolution), so the
        # ridge must sit at a fraction of the typical eigenvalue scale
        # (trace/n), not at round-off: null-space modes are pinned
        # while well-determined modes (eig >= O(trace/n)) barely move
        ridge = 1e-2 * xp.real(xp.trace(G)) / ntheta
        # the ridged Gram is constant across iterations: invert ONCE
        # (Hermitian PD, cond ~ eigmax/ridge ~ 1e3 — benign) so each
        # iteration is O(n^2) matvecs, not an O(n^3) solve
        Gr_inv = xp.linalg.inv(G + ridge * xp.eye(ntheta))
        S = xp.sqrt(xp.maximum(chunk, 0.0))
        phf_w = xp.conj(ph_f) * wfv[:, None]               # [nf_c, n]
        pht_w = xp.conj(ph_t) * wtv[None, :]               # [n, nt_c]

        def refine_body(E, _):
            mag = xp.maximum(xp.abs(E), 1e-30)
            Em = S * E / mag                               # (a)
            b = xp.sum((phf_w.T @ Em) * pht_w, axis=1)     # A^H W Em
            mu2 = Gr_inv @ b                               # (b)
            return (ph_f * mu2[None, :]) @ ph_t, None

        if scan is None:
            for _ in range(refine):
                E, _ = refine_body(E, None)
        else:
            E, _ = scan(refine_body, E, None, length=refine)
        model = xp.sum(w2d * xp.abs(E) ** 2)
        E = E * xp.sqrt(xp.maximum(flux, 0.0)
                        / xp.maximum(model, 1e-30))
    return E, conc


@functools.lru_cache(maxsize=16)
def _chunks_jax(geom, ntheta: int, niter: int, mask_fd: float,
                mask_tau: float, mesh=None, refine: int = 0):
    """jit'd all-chunks retrieval, cached on the shared chunk geometry.

    With ``mesh``, the flattened chunk axis is sharded over the mesh's
    ``data`` axis via shard_map — each device lax.maps its local chunks
    (zero cross-device communication; stitching gathers on host), so a
    survey bucket's holography scales across the slice.
    """
    import jax
    import jax.numpy as jnp

    def one(chunk, w2d, eta_c, theta_max):
        return _chunk_field_xp(chunk, w2d, eta_c, theta_max, geom, ntheta,
                               niter, mask_fd, mask_tau, xp=jnp,
                               scan=jax.lax.scan, refine=refine)

    def run_local(chunks, w2d, etas, theta_maxs):
        # lax.map, not vmap: stage 2 materialises an [nf_c, ntheta,
        # ntheta] complex intermediate per chunk (tens of MB); a vmap
        # over hundreds of chunks on a big dynspec would multiply that
        # into HBM-exhausting territory, while sequential chunks keep
        # the working set to one chunk and the per-chunk work is already
        # matmul-shaped enough to fill the device
        return jax.lax.map(lambda args: one(args[0], w2d, args[1],
                                            args[2]),
                           (chunks, etas, theta_maxs))

    if mesh is None:
        return jax.jit(run_local)

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS

    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
    shard = shard_map(
        run_local, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS)))
    return jax.jit(shard)


def retrieve_wavefield(data: DynspecData, eta: float, chunk_nf: int = 64,
                       chunk_nt: int = 64, ntheta: int | None = None,
                       niter: int = 60, mask_bins: float = 1.5,
                       theta_frac: float = 0.95, conc_weight: float = 0.0,
                       refine: int = 10,
                       refine_global: int | str = "auto",
                       backend: str = "jax") -> Wavefield:
    """Retrieve the complex wavefield of ``data`` given arc curvature
    ``eta`` (us/mHz^2, as fit by ``fit_arc`` on the non-lamsteps
    secondary spectrum, quoted at ``data.freq``).

    ``chunk_nf``/``chunk_nt`` set the Hann-windowed block size (50%
    overlap); blocks must be small enough that the curvature is locally
    constant but large enough to resolve the arc.  ``mask_bins`` masks
    the spectral origin out to that many conjugate-spectrum bins.
    ``theta_frac`` shrinks the SHARED theta span inside the observable
    (fd, tau) window; the span is one value for all chunks, capped by
    the steepest (lowest-frequency) chunk's curvature: theta_max =
    theta_frac * min(fd_max, sqrt(tau_max / max(eta_chunk))).

    ``ntheta=None`` (default) picks the theta grid from the chunk
    geometry itself: spacing fine enough to resolve BOTH conjugate axes
    — at most one Doppler bin per step, and at most one delay bin per
    step at the arc edge (min(d_fd_bin, d_tau_bin / (2*eta*theta_max)))
    — capped at 257 points.  The NUDFT sampler is exact for any
    spacing.  An explicit ``ntheta`` overrides the point count but
    keeps the span.

    ``refine`` runs that many fixed-count alternating-projection
    iterations per chunk after the eigen seed (measured magnitude /
    model phase-and-support — see _chunk_field_xp).  Measured on
    simulated Kolmogorov screens (corr of |E|^2 with the dynspec,
    chunk 32x32): mb2=20 ar=10 0.78 -> 0.94, mb2=2 ar=3 0.32 -> 0.46,
    mb2=2 ar=1 0.29 -> 0.45; converged by ~10 iterations, broad ridge
    plateau.  ``refine=0`` recovers the pure eigenvector retrieval.

    ``refine_global`` (default ``"auto"``) controls the global
    arc-support Gerchberg-Saxton pass on the STITCHED field
    (``refine_wavefield_global``): it lifts weak-scattering true-field
    fidelity 0.68-0.70 -> ~0.86 but degrades strong screens.  The auto
    rule measures the regime from the data itself — the intensity
    correlation of the stitched |E|^2 with the dynspec — and refines
    only below ``AUTO_REFINE_CORR_THRESHOLD`` (0.80), which picks the
    better-or-equal branch in all 12 cells of the ground-truth map
    (docs/wavefield.md).  Pass an int for the manual override: 0 = never
    refine, N = always N iterations.  ``Wavefield.refined_global``
    records what was applied.
    """
    dyn = np.asarray(data.dyn, dtype=np.float64)
    return retrieve_wavefield_batch(
        dyn[None], np.asarray(data.freqs, dtype=np.float64),
        np.asarray(data.times, dtype=np.float64), [eta],
        freq=float(data.freq), dt=float(data.dt), df=float(data.df),
        chunk_nf=chunk_nf, chunk_nt=chunk_nt, ntheta=ntheta,
        niter=niter, mask_bins=mask_bins, theta_frac=theta_frac,
        conc_weight=conc_weight, refine=refine,
        refine_global=refine_global, backend=backend)[0]


def retrieve_wavefield_batch(dyn_batch, freqs, times, etas,
                             freq: float | None = None,
                             dt: float | None = None,
                             df: float | None = None,
                             chunk_nf: int = 64, chunk_nt: int = 64,
                             ntheta: int | None = None, niter: int = 60,
                             mask_bins: float = 1.5,
                             theta_frac: float = 0.95,
                             conc_weight: float = 0.0, refine: int = 10,
                             refine_global: int | str = "auto",
                             mesh=None,
                             backend: str = "jax") -> list:
    """Retrieve wavefields for a BATCH of epochs sharing one grid.

    ``dyn_batch`` [B, nchan, nsub] of epochs that GENUINELY share the
    (freqs, times) grid — e.g. a fixed-setup survey's equal-shape
    epochs.  Padded buckets from ``parallel.pad_batch`` are NOT
    supported: fill rows/columns would be stitched as real signal and
    bias the flux anchor — group equal-shape epochs instead.  ``etas``
    [B] are per-epoch curvatures quoted at ``freq`` (default: the band
    centre); ``dt``/``df`` override the axis spacings (defaulting to
    the axis differences).  All epochs share the chunk plan and one
    theta grid (span capped by the steepest epoch's lowest-frequency
    chunk), so on the jax backend every chunk of every epoch runs
    through ONE compiled program; only the per-epoch phase stitching is
    host-side.  With ``mesh`` (jax backend), the flattened chunk axis
    is sharded over the mesh's ``data`` axis — embarrassingly parallel
    holography across the slice (chunk count padded to the axis size).
    Returns a list of ``Wavefield``.
    """
    backend = resolve(backend)
    if isinstance(refine_global, str):
        if refine_global != "auto":
            raise ValueError(
                f"refine_global must be 'auto' or an iteration count, "
                f"got {refine_global!r}")
    else:
        refine_global = int(refine_global)  # fail fast, pre-retrieval
    dyn_batch = np.asarray(dyn_batch, dtype=np.float64)
    if dyn_batch.ndim != 3:
        raise ValueError(f"dyn_batch must be [B, nchan, nsub], got "
                         f"shape {dyn_batch.shape}")
    etas_b = np.asarray([float(e) for e in etas], dtype=np.float64)
    if len(etas_b) != dyn_batch.shape[0]:
        raise ValueError(f"{len(etas_b)} curvatures for "
                         f"{dyn_batch.shape[0]} epochs")
    if not np.all(np.isfinite(etas_b) & (etas_b > 0)):
        raise ValueError(f"eta must be a positive finite curvature "
                         f"(us/mHz^2), got {list(etas_b)}")
    B, nchan, nsub = dyn_batch.shape
    chunk_nf = min(chunk_nf, nchan)
    chunk_nt = min(chunk_nt, nsub)
    freqs = np.asarray(freqs, dtype=np.float64)
    times = np.asarray(times, dtype=np.float64)
    dt_s = float(abs(dt)) if dt is not None else (
        float(abs(times[1] - times[0])) if len(times) > 1 else 1.0)
    df_mhz = float(abs(df)) if df is not None else (
        float(abs(freqs[1] - freqs[0])) if len(freqs) > 1 else 1.0)
    f_ref = float(np.mean(freqs)) if freq is None else float(freq)

    # shared chunk geometry (calc_sspec units: fd mHz, tau us)
    geom = (dt_s, df_mhz)
    d_fd_bin = 1e3 / (chunk_nt * dt_s)    # chunk Doppler resolution
    d_tau_bin = 1.0 / (chunk_nf * df_mhz)  # chunk delay resolution
    fd_max = 1e3 / (2 * dt_s)              # Nyquist extents of the data
    tau_max = 1.0 / (2 * df_mhz)
    mask_fd = mask_bins * d_fd_bin
    mask_tau = mask_bins * d_tau_bin

    fstarts = _chunk_starts(nchan, chunk_nf)
    tstarts = _chunk_starts(nsub, chunk_nt)
    slots = [(cf, ct) for cf in fstarts for ct in tstarts]
    K = len(slots)
    w2d = np.hanning(chunk_nf)[:, None] * np.hanning(chunk_nt)[None, :]

    # per-(epoch, chunk) curvature: eta ~ 1/f^2 across the band
    row_scale = np.array([(f_ref / float(np.mean(freqs[cf:cf + chunk_nf])))
                          ** 2 for cf in fstarts])
    chunk_scale = np.repeat(row_scale, len(tstarts))          # [K]
    eta_bc = etas_b[:, None] * chunk_scale[None, :]           # [B, K]

    # theta grid: ONE shared span for the whole batch (one compiled
    # program), capped by the STEEPEST chunk of the steepest epoch so no
    # chunk's tau = eta_c*theta^2 leaves the delay Nyquist window.
    # Unless overridden, the spacing matches the chunk resolution on
    # BOTH conjugate axes: at most the Doppler bin width, and fine
    # enough that one theta step moves the delay by at most one delay
    # bin at the arc edge (steep arcs are delay-resolved long before
    # they are Doppler-resolved).  The NUDFT sampler is exact for any
    # spacing.
    eta_hi = float(eta_bc.max())
    theta_max = theta_frac * min(fd_max, float(np.sqrt(tau_max / eta_hi)))
    if ntheta is None:
        d_th = min(d_fd_bin, d_tau_bin / (2 * eta_hi * theta_max))
        nhalf = int(np.clip(np.floor(theta_max / d_th), 4, 128))
        ntheta = 2 * nhalf + 1
    ntheta = int(ntheta)

    # flatten epochs x chunks -> one device program
    chunks = np.empty((B * K, chunk_nf, chunk_nt))
    for b in range(B):
        for k, (cf, ct) in enumerate(slots):
            chunks[b * K + k] = dyn_batch[b, cf:cf + chunk_nf,
                                          ct:ct + chunk_nt]
    etas_flat = eta_bc.reshape(-1)
    tmaxs = np.full(B * K, theta_max)

    if backend == "jax":
        import jax.numpy as jnp

        run = _chunks_jax(geom, int(ntheta), int(niter), float(mask_fd),
                          float(mask_tau), mesh, refine=int(refine))
        n_flat = chunks.shape[0]
        if mesh is not None:
            # pad the chunk axis to the data-axis size so shard_map gets
            # equal shards; dummy chunks (zero flux) are dropped after
            from ..parallel.mesh import DATA_AXIS

            nd = int(mesh.shape[DATA_AXIS])
            pad = (-n_flat) % nd
            if pad:
                chunks = np.concatenate(
                    [chunks, np.zeros((pad,) + chunks.shape[1:])])
                etas_flat = np.concatenate([etas_flat,
                                            np.full(pad, eta_hi)])
                tmaxs = np.concatenate([tmaxs, np.full(pad, theta_max)])
            # place each shard directly on its device (leading axis on
            # the data axis) — staging the whole padded tensor on device
            # 0 and letting jit reshard would put the entire bucket's
            # chunk tensor in one device's HBM
            from ..parallel.mesh import shard_leading

            chunks, etas_flat, tmaxs = shard_leading(
                (chunks, etas_flat, tmaxs), mesh)
        E_all, conc = run(jnp.asarray(chunks), jnp.asarray(w2d),
                          jnp.asarray(etas_flat), jnp.asarray(tmaxs))
        E_all = np.asarray(E_all)[:n_flat]
        conc = np.asarray(conc, dtype=np.float64)[:n_flat]
    else:
        grid_cache: dict = {}
        out = []
        last_eta = None
        for c, e, tm in zip(chunks, etas_flat, tmaxs):
            if last_eta is not None and e != last_eta:
                # chunks are epoch- then frequency-row-major and rows
                # are never revisited: drop the previous row's eta-keyed
                # phase tensors (each [nf_c, ntheta, ntheta] complex) so
                # peak cache memory stays one row, not the whole batch
                for k in [k for k in grid_cache
                          if isinstance(k, tuple) and k[1] == last_eta]:
                    del grid_cache[k]
            last_eta = e
            out.append(_chunk_field_xp(c, w2d, e, tm, geom, int(ntheta),
                                       int(niter), mask_fd, mask_tau,
                                       xp=np, cache=grid_cache,
                                       refine=int(refine)))
        E_all = np.stack([o[0] for o in out])
        conc = np.array([o[1] for o in out], dtype=np.float64)

    theta = np.linspace(-theta_max, theta_max, ntheta)
    wfs = [
        _stitch(E_all[b * K:(b + 1) * K], conc[b * K:(b + 1) * K],
                dyn_batch[b], slots, (chunk_nf, chunk_nt), w2d, freqs,
                times, float(etas_b[b]), eta_bc[b], theta,
                conc_weight=conc_weight)
        for b in range(B)
    ]
    # Round-4 auto regime rule: refine where the stitched field does
    # NOT already explain the intensity (weak/moderate screens); skip
    # where it does (strong-screen signature — the refinement's
    # single-parabola corridor would destroy real multi-arc delay
    # structure).  Per-epoch decision from measured data only; an
    # explicit int applies uniformly (0 = never).
    if refine_global == "auto":
        iters_b = [AUTO_REFINE_ITERS if auto_refine_decision(
            intensity_corr(w.field, dyn_batch[b])) else 0
            for b, w in enumerate(wfs)]
    else:
        iters_b = [int(refine_global)] * len(wfs)
    wfs = [dataclasses.replace(w, field=refine_wavefield_global(
        w.field, dyn_batch[b], df_mhz, dt_s, float(etas_b[b]),
        iters=n), refined_global=n) if n else w
        for b, (w, n) in enumerate(zip(wfs, iters_b))]
    return wfs


def field_overlap(A, B, cs: int = 32):
    """Gauge-invariant per-chunk fidelity between two complex fields:
    Hann-windowed normalised inner products |<A, B>| over the standard
    50%-overlap chunk tiling, returned as an array (random-phase floor
    ~1/cs).  THE evaluation metric for wavefield retrieval — phase-
    sensitive, insensitive to the unobservable per-chunk global phase —
    used by the CI ground-truth tests and the docs/wavefield.md regime
    map (scripts/wavefield_regime_map.py); both call this single
    definition."""
    A = np.asarray(A)
    B = np.asarray(B)
    if A.shape != B.shape:
        raise ValueError(f"field shapes differ: {A.shape} vs {B.shape}")
    # Fields smaller than the chunk in either dimension: shrink the
    # chunk (and its window) to fit rather than broadcasting a cs x cs
    # Hann against a sub-cs tile.
    cs = int(min(cs, A.shape[0], A.shape[1]))
    if cs < 3:
        # np.hanning(2) is all-zero — every chunk would have zero weight
        raise ValueError(
            f"field {A.shape} too small for field_overlap (min dim >= 3)")
    w = np.hanning(cs)[:, None] * np.hanning(cs)[None, :]
    ovs = []
    for cf in _chunk_starts(A.shape[0], cs):
        for ct in _chunk_starts(A.shape[1], cs):
            Ea, Eb = A[cf:cf + cs, ct:ct + cs], B[cf:cf + cs, ct:ct + cs]
            den = np.sqrt(np.sum(np.abs(Ea) ** 2 * w)
                          * np.sum(np.abs(Eb) ** 2 * w))
            if den > 0:
                ovs.append(abs(np.sum(Ea * np.conj(Eb) * w)) / den)
    return np.asarray(ovs)


def refine_wavefield_global(field, dyn, df, dt, eta, iters: int = 30,
                            corridor_frac: float = 0.5,
                            corridor_floor_bins: float = 5.0):
    """Global arc-support Gerchberg-Saxton refinement of a stitched
    wavefield (round-3; since round 4 applied AUTOMATICALLY by the
    retrieval APIs' default ``refine_global="auto"`` whenever the
    measured regime is weak/moderate — see ``auto_refine_decision``).

    Alternates (a) a magnitude projection — keep the model's phases,
    take |E| from the measured intensity — with (b) a support projection
    in the FIELD conjugate spectrum: zero everything outside the
    corridor |tau - eta fd^2| <= corridor_frac*|eta|*fd^2 +
    corridor_floor_bins*dtau around the single image parabola (scattered
    images live ON tau = eta fd^2 in the field spectrum, unlike the
    intensity spectrum's pairwise-difference manifold).  The corridor is
    RESTRICTIVE (~0.5% of the conjugate plane at default settings) — a
    loose mask would make the magnitude projection trivially reproduce
    the dynspec with garbage phases.

    Measured against the simulator's TRUE complex field (per-chunk
    gauge-invariant overlap, the phase-sensitive metric; see
    docs/wavefield.md regime map): weak screens mb2=2 ar=1/3 lift
    0.68/0.70 -> 0.855/0.859.  STRONG screens regress (mb2=20 ar=10:
    0.74 -> 0.63) — their delay structure overflows the single-parabola
    corridor — which is why the auto rule SKIPS them (intensity corr of
    the unrefined field >= 0.80); only force it via an explicit
    ``refine_global=N`` on data you know is weak-scattering.

    Returns the refined complex field [nchan, nsub] with total flux
    re-anchored to the data.
    """
    dyn = np.asarray(dyn, dtype=np.float64)
    amp = np.sqrt(np.maximum(dyn, 0.0))
    mask = arc_support_mask(dyn.shape, df, dt, eta,
                            corridor_frac=corridor_frac,
                            corridor_floor_bins=corridor_floor_bins)
    E = np.asarray(field, dtype=np.complex128)
    for _ in range(int(iters)):
        E = amp * np.exp(1j * np.angle(E))
        E = arc_support_project(E, mask)
    flux = float(np.sum(np.maximum(dyn, 0.0)))
    model = float(np.sum(np.abs(E) ** 2))
    if model > 0:
        E = E * np.sqrt(flux / model)
    return E


def arc_support_mask(shape, df, dt, eta, corridor_frac: float = 0.5,
                     corridor_floor_bins: float = 5.0) -> np.ndarray:
    """Boolean conjugate-plane corridor |tau - eta fd^2| <=
    corridor_frac*|eta|*fd^2 + corridor_floor_bins*dtau on the UNSHIFTED
    fft2 grid of a [nchan, nsub] field (tau us from df MHz, fd mHz from
    dt s) — the support constraint of refine_wavefield_global."""
    nf_, nt_ = shape
    tau = np.fft.fftfreq(nf_, d=abs(df))          # us
    fd = np.fft.fftfreq(nt_, d=abs(dt)) * 1e3     # mHz
    dtau = abs(tau[1]) if nf_ > 1 else 1.0
    return (np.abs(tau[:, None] - eta * fd[None, :] ** 2)
            <= corridor_frac * abs(eta) * fd[None, :] ** 2
            + corridor_floor_bins * dtau)


def arc_support_project(E, mask):
    """The (linear, idempotent) support projection: zero the field's
    conjugate spectrum outside the corridor."""
    return np.fft.ifft2(np.fft.fft2(E) * mask)


def _stitch(E_chunks, conc, dyn, slots, chunk_shape, w2d, freqs, times,
            eta, chunk_etas, theta, conc_weight: float = 0.0) -> Wavefield:
    """Overlap-add one epoch's chunk fields with per-chunk global-phase
    alignment (host-side; cheap).

    The BLEND window adds a small pedestal to the Hann analysis window:
    np.hanning is zero at its endpoints, so pure-Hann blending would
    leave the spectrum's outermost row/column of pixels (covered only by
    a chunk edge) identically zero; the pedestal gives them the nearest
    chunk's model value, and den-normalisation keeps the blend unbiased
    for any window.

    ``conc_weight`` > 0 additionally weights each chunk's contribution by
    ``(conc_k / max conc)**conc_weight`` — chunks whose theta-theta
    matrix was poorly rank-1 (low top-eigenmode energy fraction) defer
    to better-concentrated neighbours in the overlap regions; 0 keeps
    the uniform blend.  Measured on the simulator's Kolmogorov screens
    (docs/roadmap.md): ground-truth dynspec correlation is flat at
    cw<=0.5 and degrades slightly beyond (0.774 -> 0.749 at cw=4 on the
    strong-anisotropy case), so the default stays 0; the knob is kept
    for data whose chunk quality is genuinely bimodal (e.g. RFI-hit
    blocks).
    """
    chunk_nf, chunk_nt = chunk_shape
    nchan, nsub = dyn.shape
    wb2d = np.outer(np.hanning(chunk_nf) + 0.02,
                    np.hanning(chunk_nt) + 0.02)
    quality = np.ones(len(slots))
    if conc_weight > 0:
        c = np.maximum(np.nan_to_num(np.asarray(conc, dtype=np.float64)),
                       0.0)
        cmax = c.max()
        if cmax > 0:
            # floor keeps every pixel covered even if one chunk's conc
            # underflows: a zero-weight sole contributor would leave a
            # hole that the flux re-anchor then inflates
            quality = np.maximum((c / cmax) ** conc_weight, 1e-3)
    num = np.zeros((nchan, nsub), dtype=np.complex128)
    den = np.zeros((nchan, nsub), dtype=np.float64)
    align = np.full(len(slots), np.nan)
    for k, (cf, ct) in enumerate(slots):
        E_c = E_chunks[k]
        sl = (slice(cf, cf + chunk_nf), slice(ct, ct + chunk_nt))
        z = np.sum(num[sl] * np.conj(E_c) * w2d)
        norm = (np.sqrt(np.sum(np.abs(num[sl]) ** 2 * w2d))
                * np.sqrt(np.sum(np.abs(E_c) ** 2 * w2d)))
        if norm > 0 and np.abs(z) > 1e-12 * norm:
            align[k] = float(np.abs(z) / norm)
            E_c = E_c * (z / np.abs(z))
        num[sl] += quality[k] * E_c * wb2d
        den[sl] += quality[k] * wb2d
    field = num / np.maximum(den, 1e-12)
    # re-anchor the total flux: overlap-add attenuates where neighbouring
    # chunks blend imperfectly coherently
    flux = float(np.sum(np.maximum(dyn, 0.0)))
    model = float(np.sum(np.abs(field) ** 2))
    if model > 0:
        field = field * np.sqrt(flux / model)
    return Wavefield(field=field, freqs=freqs, times=times, eta=eta,
                     chunk_shape=(chunk_nf, chunk_nt), conc=conc,
                     align=align, theta=theta,
                     chunk_etas=np.asarray(chunk_etas, dtype=np.float64))
