from .arc_fit import (NormSspec, fit_arc, fit_arcs_multi,  # noqa: F401
                      make_arc_fitter, norm_sspec)
from .curvature_fit import fit_arc_curvature  # noqa: F401
from .thetatheta import (fit_arc_thetatheta,  # noqa: F401
                         theta_theta_map)
from .wavefield import (Wavefield, retrieve_wavefield,  # noqa: F401
                        retrieve_wavefield_batch)
from .filters import savgol1  # noqa: F401
from .lm import (LsqResult, least_squares_numpy, lm_fit_batched,  # noqa: F401
                 lm_fit_jax)
from .mcmc import (ensemble_sample, fit_arc_curvature_mcmc,  # noqa: F401
                   fit_scint_params_2d_mcmc, fit_scint_params_mcmc,
                   fit_scint_params_mcmc_batch,
                   fit_scint_params_sspec_mcmc)
from .scint_fit import (acf_cuts, fit_scint_params,  # noqa: F401
                        fit_scint_params_2d, fit_scint_params_2d_batch,
                        fit_scint_params_batch,
                        fit_scint_params_from_dyn, fit_scint_params_sspec,
                        initial_guesses)
