"""Least-squares engines.

The reference drives all parameter fits through lmfit's MINPACK wrapper
(``Minimizer(...).minimize()``, dynspec.py:987).  lmfit's data-dependent
iteration counts cannot vmap, so the TPU path here is a **fixed-iteration
Levenberg–Marquardt** with box bounds by projection: every epoch in a batch
runs the same instruction stream (SPMD-uniform), making ``vmap``/``pmap``
over thousands of epochs trivial.  The numpy path wraps
``scipy.optimize.least_squares`` (same convergence class as lmfit) for the
reference-equivalent CPU behaviour.

Both return :class:`LsqResult` with lmfit-style stderr: the square root of
``diag(inv(J^T J) * redchi)``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Sequence

import numpy as np

from .. import obs


@dataclasses.dataclass(frozen=True)
class LsqResult:
    params: Any       # [P] best-fit vector
    stderr: Any       # [P] 1-sigma errors (lmfit-style scaled covariance)
    cov: Any          # [P, P]
    redchi: Any       # reduced chi^2
    cost: Any         # 0.5 * sum(residual^2) at optimum


def _register():
    try:
        import jax

        jax.tree_util.register_pytree_node(
            LsqResult,
            lambda r: ((r.params, r.stderr, r.cov, r.redchi, r.cost), None),
            lambda _, l: LsqResult(*l))
    except ImportError:  # pragma: no cover
        pass


_register()


def _jtj(xp, J):
    """``J^T J`` with a tail-padding-STABLE reduction order: one GEMM
    per fixed 128-row block, then an across-block sum.  An all-zero
    padded tail contributes exact ``+0`` blocks, so the split
    pipeline's canonicalised (rung-padded, ``buckets.vector_rung``)
    residual vectors yield bit-identical normal equations to the
    unpadded fit.  XLA's single fused GEMM does NOT have this property
    — measured on CPU, ``J.T @ J`` over ``[N+pad, P]`` drifts by ulps
    when the padded length changes, which through 20 LM iterations
    breaks the split path's CSV byte-equality contract.  (The 1-D dots
    ``r @ r`` / ``J.T @ r`` reduce per-output sequentially and ARE
    tail-padding-exact; only the GEMM needs this.)"""
    if xp is np:
        return J.T @ J
    import jax.numpy as jnp

    n, p = J.shape
    pad = (-int(n)) % 128
    Jb = jnp.pad(J, ((0, pad), (0, 0))).reshape(-1, 128, p)
    return jnp.sum(jnp.einsum("bip,biq->bpq", Jb, Jb), axis=0)


def _covariance(xp, J, r, n_par, nobs=None):
    """lmfit-style scaled covariance: inv(J^T J) * redchi.

    ``nobs`` overrides the observation count when the residual vector
    is TAIL-PADDED with exact zeros (the split pipeline's canonicalised
    fitter unit): padded entries contribute nothing to ``r @ r`` or
    ``J^T J``, but ``r.shape[0]`` would inflate the dof and shrink
    redchi/stderr.  Pass the real count (a traced scalar is fine)."""
    n = r.shape[0] if nobs is None else nobs
    dof = max(n - n_par, 1) if isinstance(n, int) else n - n_par
    if nobs is not None and not isinstance(n, int):
        dof = xp.maximum(n - n_par, 1)
    if xp is np:
        redchi = (r @ r) / dof
    else:
        # divide via an EXPLICIT same-dtype reciprocal-multiply: when
        # dof is a compile-time constant (the fused single-program
        # pipeline) XLA's algebraic simplifier rewrites x / const into
        # x * (1/const), while a runtime dof (the split back-end unit,
        # where nobs is an input) keeps the true division — measured as
        # a 1-ulp redchi/stderr fork between the two programs.  Writing
        # the reciprocal-multiply ourselves puts both on the identical
        # rounding path (the folded 1/const equals the runtime f32
        # divide).
        dof = xp.asarray(dof, dtype=r.dtype)
        redchi = (r @ r) * (xp.asarray(1.0, dtype=r.dtype) / dof)
    JTJ = _jtj(xp, J)
    cov = xp.linalg.inv(JTJ + 1e-300 * xp.eye(n_par)) * redchi
    return cov, redchi


def least_squares_numpy(residual_fn: Callable, p0, bounds=None,
                        args: Sequence = ()) -> LsqResult:
    """scipy TRF least squares (CPU path)."""
    from scipy.optimize import least_squares as _ls

    p0 = np.asarray(p0, dtype=np.float64)
    if bounds is None:
        lo, hi = -np.inf, np.inf
    else:
        lo = np.asarray(bounds[0], dtype=np.float64)
        hi = np.asarray(bounds[1], dtype=np.float64)
        # TRF requires a strictly interior start
        hi_in = np.where(np.isfinite(hi), hi - 1e-12, hi)
        lo_in = np.where(np.isfinite(lo), lo + 1e-12, lo)
        p0 = np.clip(p0, lo_in, hi_in)
    with obs.span("fit.lsq_numpy") as sp:
        sol = _ls(lambda p: np.asarray(residual_fn(p, *args),
                                       dtype=np.float64),
                  p0, bounds=(lo, hi))
        cost = 0.5 * sol.fun @ sol.fun
        if obs.enabled():
            # data-dependent convergence accounting (the jax path is
            # fixed-iteration by construction: its count is the lm_steps
            # counter recorded per executed batch by the driver)
            sp.set(nfev=int(sol.nfev), status=int(sol.status),
                   cost=float(cost))
            obs.inc("lsq_nfev", int(sol.nfev))
            obs.inc("lsq_fits")
    cov, redchi = _covariance(np, sol.jac, sol.fun, p0.size)
    return LsqResult(params=sol.x, stderr=np.sqrt(np.abs(np.diag(cov))),
                     cov=cov, redchi=redchi, cost=cost)


def outlined_call(fn: Callable, *args):
    """Run ``fn(*args)`` as its OWN XLA computation inside the caller's
    jit — a conditional branch of a 2-trip ``lax.scan``.

    Why: XLA compiles the MAIN program graph with whole-module fusion
    (FMA grouping, reassociation) that depends on everything around a
    subgraph, so the same ``fn`` traced into two different programs can
    produce results differing by 1 ulp.  Branch (and loop-body)
    computations compile per-computation, giving ``fn`` the identical
    instruction stream in every enclosing program.  The split pipeline
    leans on this: its shape-stable fitter unit and the fused
    single-program step both route the scint LM fit through ONE
    outlined computation, which is what makes their CSV outputs
    byte-identical (a plain inline trace measurably drifts).  A 1-trip
    loop would be inlined by XLA's while-loop simplifier; the second
    trip runs the identity branch, so ``fn`` still executes once."""
    import jax
    import jax.numpy as jnp

    shapes = jax.eval_shape(fn, *args)
    zeros = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    def body(carry, i):
        out = jax.lax.cond(i == 0, lambda: fn(*args), lambda: carry)
        return out, None

    out, _ = jax.lax.scan(body, zeros, jnp.arange(2))
    return out


def lm_fit_jax(residual_fn: Callable, p0, bounds=None, args: Sequence = (),
               steps: int = 30, lam0: float = 1e-3, lam_up: float = 10.0,
               lam_down: float = 0.3, nobs=None, steps_rt=None):
    """Fixed-iteration damped LM with box projection; fully jittable and
    vmappable (no data-dependent control flow; rejected steps raise the
    damping instead of re-solving).

    residual_fn(p, *args) -> [N]; p0 [P].  Returns LsqResult of jax arrays.
    ``nobs`` is the REAL observation count when the residual vector is
    tail-padded with exact zeros (see :func:`_covariance`).

    ``steps_rt`` (optional TRACED scalar) bounds the iteration count at
    RUNTIME: the loop becomes a ``lax.while_loop`` running
    ``min(steps_rt, steps)`` trips, so a warm-started caller (the
    streaming plane seeding from the previous tick's converged
    parameters) genuinely skips device iterations without changing the
    program's input signature — ``steps`` stays the static trip ceiling
    and the compile cache key.  ``None`` keeps the historical
    ``lax.scan`` path byte-for-byte.
    """
    import jax
    import jax.numpy as jnp

    p0 = jnp.asarray(p0, dtype=jnp.result_type(float))
    n_par = p0.shape[0]
    if bounds is not None:
        lo = jnp.asarray(bounds[0], dtype=p0.dtype)
        hi = jnp.asarray(bounds[1], dtype=p0.dtype)
        project = lambda p: jnp.clip(p, lo, hi)  # noqa: E731
    else:
        project = lambda p: p  # noqa: E731

    def step(state, _):
        # residual and cost at the current point ride in the carry, so each
        # iteration evaluates residual_fn once (at the trial point) plus one
        # jacobian — a rejected step reuses the carried (r, c) unchanged
        p, r, c, lam = state
        J = jax.jacfwd(residual_fn)(p, *args)
        g = J.T @ r
        JTJ = _jtj(jnp, J)
        damp = lam * jnp.diag(jnp.diag(JTJ)) + 1e-12 * jnp.eye(n_par)
        dp = jnp.linalg.solve(JTJ + damp, -g)
        p_try = project(p + dp)
        r_try = residual_fn(p_try, *args)
        c_try = 0.5 * (r_try @ r_try)
        better = c_try < c
        p_new = jnp.where(better, p_try, p)
        r_new = jnp.where(better, r_try, r)
        c_new = jnp.where(better, c_try, c)
        lam_new = jnp.where(better, lam * lam_down, lam * lam_up)
        return (p_new, r_new, c_new, lam_new), None

    p_init = project(p0)
    r0 = residual_fn(p_init, *args)
    c0 = 0.5 * (r0 @ r0)
    init = (p_init, r0, c0, jnp.asarray(lam0, dtype=p0.dtype))
    if steps_rt is None:
        (p_fin, r, c_fin, _), _ = jax.lax.scan(step, init, length=steps)
    else:
        limit = jnp.minimum(jnp.asarray(steps_rt, dtype=jnp.int32),
                            jnp.int32(steps))

        def cond(carry):
            i, _ = carry
            return i < limit

        def body(carry):
            i, state = carry
            state, _ = step(state, None)
            return i + 1, state

        _, (p_fin, r, c_fin, _) = jax.lax.while_loop(
            cond, body, (jnp.int32(0), init))
    J = jax.jacfwd(residual_fn)(p_fin, *args)
    cov, redchi = _covariance(jnp, J, r, n_par, nobs=nobs)
    return LsqResult(params=p_fin, stderr=jnp.sqrt(jnp.abs(jnp.diag(cov))),
                     cov=cov, redchi=redchi, cost=c_fin)


@functools.lru_cache(maxsize=None)
def _batched_lm(residual_fn, steps, n_batched_args):
    """jit'd vmap of lm_fit_jax over leading batch axes of p0/bounds/args."""
    import jax

    def single(p0, lo, hi, *args):
        return lm_fit_jax(residual_fn, p0, bounds=(lo, hi), args=args,
                          steps=steps)

    inner = jax.vmap(single, in_axes=(0, None, None) + (0,) * n_batched_args)
    return jax.jit(inner)


def lm_fit_batched(residual_fn: Callable, p0, bounds, args: Sequence,
                   steps: int = 30) -> LsqResult:
    """Fit B independent problems at once: p0 [B, P], every element of
    ``args`` [B, ...]; bounds shared.  ``residual_fn`` must be a module-level
    (hashable) function for the jit cache."""
    fn = _batched_lm(residual_fn, steps, len(args))
    return fn(p0, bounds[0], bounds[1], *args)
