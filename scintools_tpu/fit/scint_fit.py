"""Scintillation-parameter fitting: tau_d and dnu_d from 1-D ACF cuts.

Reference: ``Dynspec.get_scint_params(method='acf1d')``
(dynspec.py:928-1033): take the central positive-lag row/column cuts of the
2-D ACF, build initial guesses (white-noise spike from the first lag drop,
tau at 1/e, dnu at half power), and least-squares fit the joint
tau/dnu/amp/wn model with alpha fixed (default Kolmogorov 5/3) or free.

The cut/guess construction is reproduced exactly, including the reference's
``linspace(0, n, n)`` lag axes (step n/(n-1), not arange — dynspec.py:950,
952).  The fit itself runs on either engine:

* backend='numpy': scipy least squares (CPU, lmfit-equivalent class);
* backend='jax': fixed-iteration LM; :func:`fit_scint_params_batch` vmaps
  it over a [B, 2nf, 2nt] stack of ACFs for the batched-fit benchmark
  (BASELINE config 2).
"""

from __future__ import annotations

import functools

import numpy as np

from .. import obs
from ..backend import resolve
from ..data import ScintParams
from ..models.acf_models import scint_acf_model
from .lm import least_squares_numpy, lm_fit_jax

_ALPHA_KOLMOGOROV = 5 / 3


def acf_cuts(acf2d, dt, df, nchan: int, nsub: int, xp=np):
    """Central positive-lag cuts of the [2nf, 2nt] ACF and their lag axes
    (dynspec.py:949-952)."""
    ydata_f = acf2d[..., nchan:, nsub]
    ydata_t = acf2d[..., nchan, nsub:]
    nf_, nt_ = ydata_f.shape[-1], ydata_t.shape[-1]
    xdata_f = df * xp.linspace(0, nf_, nf_)
    xdata_t = dt * xp.linspace(0, nt_, nt_)
    return xdata_t, ydata_t, xdata_f, ydata_f


def initial_guesses(xdata_t, ydata_t, xdata_f, ydata_f, xp=np):
    """wn from the zero-lag spike, amp from the first real lag, tau at 1/e,
    dnu at half power (dynspec.py:965-972).  argmin-based: jit-safe."""
    wn = xp.minimum(ydata_f[..., 0] - ydata_f[..., 1],
                    ydata_t[..., 0] - ydata_t[..., 1])
    amp = xp.maximum(ydata_f[..., 1], ydata_t[..., 1])
    tau = xp.take_along_axis(
        xdata_t if xdata_t.ndim == ydata_t.ndim else xp.broadcast_to(
            xdata_t, ydata_t.shape),
        xp.argmin(xp.abs(ydata_t - amp[..., None] / np.e), axis=-1)[..., None],
        axis=-1)[..., 0]
    dnu = xp.take_along_axis(
        xdata_f if xdata_f.ndim == ydata_f.ndim else xp.broadcast_to(
            xdata_f, ydata_f.shape),
        xp.argmin(xp.abs(ydata_f - amp[..., None] / 2), axis=-1)[..., None],
        axis=-1)[..., 0]
    return tau, dnu, amp, wn


def _residual_fixed_alpha(p, x_t, x_f, y, alpha):
    import jax.numpy as jnp

    tau, dnu, amp, wn = p[0], p[1], p[2], p[3]
    model = scint_acf_model(x_t, x_f, tau, dnu, amp, wn, alpha, xp=jnp)
    return y - model


def _residual_free_alpha(p, x_t, x_f, y):
    import jax.numpy as jnp

    tau, dnu, amp, wn, alpha = p[0], p[1], p[2], p[3], p[4]
    model = scint_acf_model(x_t, x_f, tau, dnu, amp, wn, alpha, xp=jnp)
    return y - model


def fit_scint_params(acf2d, dt, df, nchan: int, nsub: int,
                     alpha: float | None = _ALPHA_KOLMOGOROV,
                     backend: str = "numpy", steps: int = 20
                     ) -> ScintParams:
    """Fit tau/dnu/amp/wn (alpha fixed unless ``alpha=None``) to one ACF.

    steps=20 everywhere in this module: measured convergence on
    simulated epochs (docs/roadmap.md round-2 entry) — the 1-D fit is
    within 0.05 sigma of an 80-step reference by 20 steps, the 2-D
    fit's measurable lanes within 1e-5 sigma; locked by
    tests/test_fit.py::test_lm_steps_default_is_converged.
    """
    backend = resolve(backend)
    # host-side validity check before dispatching to either engine (the
    # jit'd jax fit would otherwise silently return NaN parameters); one
    # host copy, same slicing as the fit consumes
    a = np.asarray(acf2d, dtype=np.float64)
    x_t, y_t, x_f, y_f = acf_cuts(a, dt, df, nchan, nsub, xp=np)
    if not (np.isfinite(y_t).all() and np.isfinite(y_f).all()):
        raise ValueError(
            "ACF cuts contain non-finite values — refill/zap the "
            "dynamic spectrum before fitting scintillation parameters")
    with obs.span("fit.scint", backend=backend) as sp:
        if backend == "numpy":
            tau0, dnu0, amp0, wn0 = initial_guesses(x_t, y_t, x_f, y_f,
                                                    xp=np)
            y = np.concatenate([y_t, y_f])
            free = alpha is None

            def resid(p):
                a_ = p[4] if free else alpha
                return y - scint_acf_model(x_t, x_f, p[0], p[1], p[2],
                                           p[3], a_, xp=np)

            p0 = ([tau0, dnu0, amp0, wn0]
                  + ([_ALPHA_KOLMOGOROV] if free else []))
            # tiny positive floors keep tau/dnu off the singular boundary
            lo = [1e-10, 1e-10, 0.0, 0.0] + ([0.0] if free else [])
            hi = [np.inf] * 4 + ([8.0] if free else [])
            res = least_squares_numpy(resid, np.asarray(p0),
                                      bounds=(lo, hi))
            out = _to_scint_params(res, alpha, np)
        else:
            obs.inc("lm_steps", steps)
            out = obs.fence(_fit_scint_jax(alpha, steps, False)(
                acf2d, float(dt), float(df), nchan, nsub))
        if obs.enabled():
            # convergence residual (an eager device sync, so only when
            # someone is watching); both branches set out.redchi
            try:
                sp.set(redchi=float(np.asarray(out.redchi)))
            except Exception:
                pass
    return out


def fit_scint_params_batch(acf2d_batch, dt, df, nchan: int, nsub: int,
                           alpha: float | None = _ALPHA_KOLMOGOROV,
                           steps: int = 20) -> ScintParams:
    """Batched jax fit: acf2d [B, 2nf, 2nt], dt/df scalars or [B].

    No ``lm_steps`` accounting here: this entry point runs at TRACE time
    inside the batched step, where a counter would fire once per compile
    and undercount steady-state executions — run_pipeline increments
    ``lm_steps`` host-side per executed batch instead.
    """
    import jax.numpy as jnp

    dt = jnp.broadcast_to(jnp.asarray(dt, dtype=jnp.result_type(float)),
                          (acf2d_batch.shape[0],))
    df = jnp.broadcast_to(jnp.asarray(df, dtype=jnp.result_type(float)),
                          (acf2d_batch.shape[0],))
    return _fit_scint_jax(alpha, steps, True)(acf2d_batch, dt, df, nchan,
                                              nsub)


def _to_scint_params(res, alpha, xp) -> ScintParams:
    free = alpha is None
    return ScintParams(
        tau=res.params[..., 0], tauerr=res.stderr[..., 0],
        dnu=res.params[..., 1], dnuerr=res.stderr[..., 1],
        amp=res.params[..., 2], wn=res.params[..., 3],
        talpha=res.params[..., 4] if free else alpha,
        talphaerr=res.stderr[..., 4] if free else None,
        redchi=res.redchi)


def _fit_scint_single_from_cuts(y_t, y_f, dt, df, alpha, steps):
    """LM fit of the joint tau/dnu model from the two 1-D ACF cuts
    (jax; called under vmap/jit by the batch entry points)."""
    import jax.numpy as jnp

    free = alpha is None
    nt_, nf_ = y_t.shape[-1], y_f.shape[-1]
    x_t = dt * jnp.linspace(0, nt_, nt_)
    x_f = df * jnp.linspace(0, nf_, nf_)
    tau0, dnu0, amp0, wn0 = initial_guesses(x_t, y_t, x_f, y_f, xp=jnp)
    y = jnp.concatenate([y_t, y_f])
    if free:
        p0 = jnp.stack([tau0, dnu0, amp0, wn0,
                        jnp.asarray(_ALPHA_KOLMOGOROV)])
        lo = jnp.array([1e-10, 1e-10, 0.0, 0.0, 0.0])
        hi = jnp.array([jnp.inf, jnp.inf, jnp.inf, jnp.inf, 8.0])
        return lm_fit_jax(_residual_free_alpha, p0, bounds=(lo, hi),
                          args=(x_t, x_f, y), steps=steps)
    p0 = jnp.stack([tau0, dnu0, amp0, wn0])
    lo = jnp.array([1e-10, 1e-10, 0.0, 0.0])
    hi = jnp.full(4, jnp.inf)
    return lm_fit_jax(_residual_fixed_alpha, p0, bounds=(lo, hi),
                      args=(x_t, x_f, y, alpha), steps=steps)


@functools.lru_cache(maxsize=None)
def _fit_scint_from_dyn_jax(alpha, steps, cuts_method="fft",
                            acf_lens="exact"):
    """Batched fit STRAIGHT from the dynspec batch: the 1-D cuts are
    computed with padded 1-D FFT reductions (ops.acf.acf_cuts_direct),
    never materialising the [B, 2nf, 2nt] 2-D ACF — the fast path of the
    batched pipeline.  ``cuts_method="matmul"`` uses the MXU Gram-matrix
    route for the cuts instead of 1-D FFTs.

    The LM runs over the CANONICALISED rung-padded concatenated cuts
    (``scint_cat_front`` + ``fit_scint_params_cat``) — the exact
    machinery the split pipeline's two-program route uses, so the fused
    and split paths trace structurally identical fitter bodies and
    their fits are bit-identical (padding with exact zeros + the
    padding-stable ``_jtj`` reduction make the rung length
    numerically inert)."""
    import jax
    import jax.numpy as jnp

    from .. import buckets
    from ..ops.acf import acf_cuts_direct

    @jax.jit
    def impl(dyn_batch, dt, df):
        cut_t, cut_f = acf_cuts_direct(dyn_batch, backend="jax",
                                       method=cuts_method, lens=acf_lens)
        nt_, nf_ = cut_t.shape[-1], cut_f.shape[-1]
        rung = buckets.vector_rung(nt_ + nf_)
        parts = scint_cat_front(cut_t, cut_f, dt, df, rung)
        aux = scint_cat_statics(nt_, nf_, rung)
        return fit_scint_params_cat(
            parts["scint_y"], parts["scint_p0"], aux["scint_nobs"],
            parts["scint_x"], aux["scint_is_t"], aux["scint_spike"],
            parts["scint_xmax"], aux["scint_valid"],
            alpha=alpha, steps=steps)

    return impl


def fit_scint_params_from_dyn(dyn_batch, dt, df,
                              alpha: float | None = _ALPHA_KOLMOGOROV,
                              steps: int = 20,
                              cuts_method: str = "fft",
                              acf_lens: str = "exact") -> ScintParams:
    """tau/dnu fits for a [B, nf, nt] dynspec batch via direct ACF cuts
    (identical results to the 2-D-ACF route; much less FFT work).
    Like :func:`fit_scint_params_batch`, no trace-time ``lm_steps``
    accounting here — the driver counts per executed batch."""
    import jax.numpy as jnp

    dt = jnp.broadcast_to(jnp.asarray(dt, dtype=jnp.result_type(float)),
                          (dyn_batch.shape[0],))
    df = jnp.broadcast_to(jnp.asarray(df, dtype=jnp.result_type(float)),
                          (dyn_batch.shape[0],))
    return _fit_scint_from_dyn_jax(alpha, steps, cuts_method, acf_lens)(
        dyn_batch, dt, df)


@functools.lru_cache(maxsize=None)
def _fit_scint_jax(alpha, steps, batched):
    import jax
    import jax.numpy as jnp

    def single(acf2d, dt, df, nchan, nsub):
        # slice the central cuts, then share the guess/bounds/LM body with
        # the from-dyn fast path (one source of truth)
        y_f = acf2d[..., nchan:, nsub]
        y_t = acf2d[..., nchan, nsub:]
        return _fit_scint_single_from_cuts(y_t, y_f, dt, df, alpha, steps)

    if batched:
        fn = jax.vmap(single, in_axes=(0, 0, 0, None, None))
    else:
        fn = single

    @functools.partial(jax.jit, static_argnums=(3, 4))
    def impl(acf2d, dt, df, nchan, nsub):
        return _to_scint_params(fn(acf2d, dt, df, nchan, nsub), alpha, jnp)

    return impl


# ---------------------------------------------------------------------------
# split-pipeline fitter unit (PipelineConfig.split_programs): the LM fit
# over CANONICALISED concatenated cut vectors.  The front-end (shape-
# keyed) computes the cuts, lag axes and initial guesses exactly as the
# fused path does, concatenates them in the fused path's order and
# TAIL-pads to a closed rung length (buckets.vector_rung) — so the real
# elements sit at identical positions, every reduction in the LM fit
# accumulates the same values in the same order plus exact trailing
# zeros, and the fit is bit-identical to the fused path while ONE
# compiled fitter program serves every (nf, nt).
# ---------------------------------------------------------------------------


def scint_cat_statics(nt_: int, nf_: int, pad_to: int) -> dict:
    """Host-side constants of the canonicalised cut layout: the
    time-part selector, white-noise-spike positions (each part's
    zero-lag sample) and validity mask over the padded axis, plus the
    real observation count (the LM dof — padded entries must not
    inflate it).  Keys match the split driver's back-end input dict."""
    L, nt_, nf_ = int(pad_to), int(nt_), int(nf_)
    if nt_ + nf_ > L:
        raise ValueError(f"scint_cat_statics: cuts {nt_}+{nf_} exceed "
                         f"rung {L}")
    is_t = np.zeros(L, dtype=bool)
    is_t[:nt_] = True
    spike = np.zeros(L, dtype=np.float32)
    spike[0] = 1.0
    spike[nt_] = 1.0
    valid = np.zeros(L, dtype=bool)
    valid[:nt_ + nf_] = True
    return {"scint_is_t": is_t, "scint_spike": spike,
            "scint_valid": valid,
            "scint_nobs": np.float32(nt_ + nf_)}


def scint_cat_front(cut_t, cut_f, dt, df, pad_to: int) -> dict:
    """TRACED front half of the split scint fit: the per-epoch
    concatenated, tail-padded cut vector [B, pad_to], the matching
    per-lane padded lag axis / per-part taper scales, and the
    initial-guess vector [B, 4] — everything whose computation needs
    the static (nt, nf).  Guesses and axes are computed with the exact
    expressions of :func:`_fit_scint_single_from_cuts`, and the fused
    fast path (:func:`_fit_scint_from_dyn_jax`) routes through THIS
    same packer, so split and fused programs trace structurally
    identical LM bodies — the basis of the bit-identity contract."""
    import jax
    import jax.numpy as jnp

    nt_, nf_ = cut_t.shape[-1], cut_f.shape[-1]
    L = int(pad_to)
    pad = L - (nt_ + nf_)
    B = cut_t.shape[0]
    dt_b = jnp.broadcast_to(jnp.asarray(dt, dtype=jnp.result_type(float)),
                            (B,))
    df_b = jnp.broadcast_to(jnp.asarray(df, dtype=jnp.result_type(float)),
                            (B,))

    def one(y_t, y_f, dt_, df_):
        x_t = dt_ * jnp.linspace(0, nt_, nt_)
        x_f = df_ * jnp.linspace(0, nf_, nf_)
        tau0, dnu0, amp0, wn0 = initial_guesses(x_t, y_t, x_f, y_f,
                                                xp=jnp)
        y = jnp.concatenate([y_t, y_f])
        x = jnp.concatenate([x_t, x_f])
        # each part's own lag maximum = the triangle-taper scale (a
        # per-part reduction the shape-stable unit cannot recover from
        # the concat)
        xmax = jnp.concatenate([jnp.broadcast_to(jnp.max(x_t), (nt_,)),
                                jnp.broadcast_to(jnp.max(x_f), (nf_,))])
        # tail-pad: zeros for the data (exact-zero residuals), last
        # value for the axis/taper vectors (finite model values under
        # the mask)
        return (jnp.pad(y, (0, pad)), jnp.pad(x, (0, pad), mode="edge"),
                jnp.pad(xmax, (0, pad), mode="edge"),
                jnp.stack([tau0, dnu0, amp0, wn0]))

    y, x, xmax, g = jax.vmap(one)(cut_t, cut_f, dt_b, df_b)
    return {"scint_y": y, "scint_x": x, "scint_xmax": xmax,
            "scint_p0": g}


def _residual_cat_fixed(p, x, is_t, spike, xmax, valid, y, alpha):
    import jax.numpy as jnp

    from ..models.acf_models import scint_acf_model_cat

    model = scint_acf_model_cat(x, is_t, spike, xmax, p[0], p[1], p[2],
                                p[3], alpha, xp=jnp)
    return jnp.where(valid, y - model, 0.0)


def _residual_cat_free(p, x, is_t, spike, xmax, valid, y):
    import jax.numpy as jnp

    from ..models.acf_models import scint_acf_model_cat

    model = scint_acf_model_cat(x, is_t, spike, xmax, p[0], p[1], p[2],
                                p[3], p[4], xp=jnp)
    return jnp.where(valid, y - model, 0.0)


@functools.lru_cache(maxsize=None)
def _fit_scint_cat_jax(alpha, steps, dynamic=False):
    import jax
    import jax.numpy as jnp

    free = alpha is None

    def single(y, g, nobs, x, is_t, spike, xmax, valid, steps_rt=None):
        if free:
            p0 = jnp.concatenate(
                [g, jnp.asarray([_ALPHA_KOLMOGOROV], dtype=g.dtype)])
            lo = jnp.array([1e-10, 1e-10, 0.0, 0.0, 0.0])
            hi = jnp.array([jnp.inf, jnp.inf, jnp.inf, jnp.inf, 8.0])
            return lm_fit_jax(_residual_cat_free, p0, bounds=(lo, hi),
                              args=(x, is_t, spike, xmax, valid, y),
                              steps=steps, nobs=nobs, steps_rt=steps_rt)
        lo = jnp.array([1e-10, 1e-10, 0.0, 0.0])
        hi = jnp.full(4, jnp.inf)
        return lm_fit_jax(_residual_cat_fixed, g, bounds=(lo, hi),
                          args=(x, is_t, spike, xmax, valid, y, alpha),
                          steps=steps, nobs=nobs, steps_rt=steps_rt)

    def impl(y, g, nobs, x, is_t, spike, xmax, valid, steps_rt=None):
        # the WHOLE vmapped fit runs as one outlined computation
        # (lm.outlined_call): identical instruction stream whether this
        # traces into the fused single-program step or the split
        # pipeline's back-end unit — the other half (beside rung
        # padding) of the split path's bit-identity contract
        from .lm import outlined_call

        if dynamic:
            # runtime iteration bound shared across the batch (the
            # streaming warm-start): one extra SCALAR input, vmapped
            # with in_axes=None so the while-loop trip count stays
            # batch-uniform
            res = outlined_call(
                lambda: jax.vmap(
                    single,
                    in_axes=(0, 0, None, 0, None, None, 0, None, None))(
                    y, g, nobs, x, is_t, spike, xmax, valid, steps_rt))
        else:
            res = outlined_call(
                lambda: jax.vmap(
                    single,
                    in_axes=(0, 0, None, 0, None, None, 0, None))(
                    y, g, nobs, x, is_t, spike, xmax, valid))
        return _to_scint_params(res, alpha, jnp)

    return impl


def fit_scint_params_cat(y, p0, nobs, x, is_t, spike, xmax, valid,
                         alpha: float | None = _ALPHA_KOLMOGOROV,
                         steps: int = 20, steps_rt=None) -> ScintParams:
    """Batched tau/dnu fit over canonicalised concatenated ACF cuts —
    the shape-stable back-end unit of the split pipeline.  All grid-
    derived vectors arrive as runtime inputs, so the traced program
    depends only on (rung length, alpha, steps): every (nf, nt) whose
    cuts pad onto the same rung reuses one compiled program.  Results
    on the real elements are bit-identical to
    :func:`fit_scint_params_from_dyn` (tier-1-asserted via the CSV
    byte-equality gate in tests/test_split_programs.py).

    ``steps_rt`` (optional traced scalar) runtime-bounds the LM trip
    count below the static ``steps`` ceiling — the streaming plane's
    warm-started ticks pass the previous tick's convergence budget here
    while the program cache key (rung, alpha, steps) stays unchanged."""
    if steps_rt is None:
        return _fit_scint_cat_jax(alpha, int(steps))(
            y, p0, nobs, x, is_t, spike, xmax, valid)
    return _fit_scint_cat_jax(alpha, int(steps), dynamic=True)(
        y, p0, nobs, x, is_t, spike, xmax, valid, steps_rt)


# ---------------------------------------------------------------------------
# 2-D ACF fit (tau, dnu, amp, wn, tilt)
# ---------------------------------------------------------------------------


def acf_lags_2d(dt, df, crop_t: int, crop_f: int, xp=np):
    """Signed lag axes of a central [2*crop_f+1, 2*crop_t+1] ACF window."""
    x_t = dt * xp.arange(-crop_t, crop_t + 1)
    x_f = df * xp.arange(-crop_f, crop_f + 1)
    return x_t, x_f


def acf2d_crop_sizes(nchan: int, nsub: int, crop_frac: float) -> tuple:
    """Central-window half-sizes (crop_t, crop_f) of the 2-D ACF fit —
    the single source of the crop rule, shared with the MCMC sampler so
    both always score the same window."""
    return (max(2, int(nsub * crop_frac / 2)),
            max(2, int(nchan * crop_frac / 2)))


def _crop_acf_2d(acf2d, nchan, nsub, crop_t, crop_f):
    return acf2d[..., nchan - crop_f: nchan + crop_f + 1,
                 nsub - crop_t: nsub + crop_t + 1]


def fit_scint_params_2d(acf2d, dt, df, nchan: int, nsub: int,
                        alpha: float | None = _ALPHA_KOLMOGOROV,
                        crop_frac: float = 0.5, backend: str = "numpy",
                        steps: int = 20):
    """Fit the 2-D ACF model (models.scint_acf_model_2d — the reference's
    empty ``acf2d`` method, dynspec.py:953-957 / scint_models.py:108-112)
    over a central window of the 2-D ACF.

    Fits (tau, dnu, amp, wn, tilt), plus the power-law index when
    ``alpha=None`` (free alpha, as on the 1-D path).  The extra ``tilt``
    (s/MHz) measures the phase-gradient shear invisible to the 1-D cuts.
    Returns (ScintParams, tilt, tilterr).
    """
    from ..models.acf_models import scint_acf_model_2d

    backend = resolve(backend)
    crop_t, crop_f = acf2d_crop_sizes(nchan, nsub, crop_frac)
    a = np.asarray(acf2d, dtype=np.float64)
    win = _crop_acf_2d(a, nchan, nsub, crop_t, crop_f)
    x_t, x_f = acf_lags_2d(float(dt), float(abs(df)), crop_t, crop_f,
                           xp=np)

    # initial guesses from the 1-D cuts machinery
    xt1, yt1, xf1, yf1 = acf_cuts(a, dt, abs(df), nchan, nsub, xp=np)
    tau0, dnu0, amp0, wn0 = initial_guesses(xt1, yt1, xf1, yf1, xp=np)
    free = alpha is None
    p0 = np.array([float(tau0), float(dnu0), float(amp0), float(wn0), 0.0]
                  + ([_ALPHA_KOLMOGOROV] if free else []))
    lo = [1e-10, 1e-10, 0.0, 0.0, -np.inf] + ([0.0] if free else [])
    hi = [np.inf] * 5 + ([8.0] if free else [])

    # taper scales = FULL scan extents (the ACF's finite-scan bias is set
    # by the observation length, not by our fit window)
    tmax, fmax = float(dt) * nsub, float(abs(df)) * nchan

    if backend == "numpy":
        def resid(p):
            a_ = p[5] if free else alpha
            m = scint_acf_model_2d(x_t, x_f, p[0], p[1], p[2], p[3],
                                   a_, p[4], tmax=tmax, fmax=fmax,
                                   xp=np)
            return (win - m).ravel()

        res = least_squares_numpy(resid, p0, bounds=(lo, hi))
        params, stderr = np.asarray(res.params), np.asarray(res.stderr)
        redchi = float(res.redchi)
    else:
        import jax.numpy as jnp

        def resid_j(p, w, xt, xf):
            a_ = p[5] if free else alpha
            m = scint_acf_model_2d(xt, xf, p[0], p[1], p[2], p[3],
                                   a_, p[4], tmax=tmax, fmax=fmax,
                                   xp=jnp)
            return (w - m).ravel()

        res = lm_fit_jax(resid_j, jnp.asarray(p0),
                         bounds=(jnp.asarray(lo), jnp.asarray(hi)),
                         args=(jnp.asarray(win), jnp.asarray(x_t),
                               jnp.asarray(x_f)), steps=steps)
        params, stderr = np.asarray(res.params), np.asarray(res.stderr)
        redchi = float(np.asarray(res.redchi))

    sp = ScintParams(tau=params[0], tauerr=stderr[0], dnu=params[1],
                     dnuerr=stderr[1], amp=params[2], wn=params[3],
                     talpha=float(params[5]) if free else alpha,
                     talphaerr=float(stderr[5]) if free else None,
                     redchi=redchi)
    return sp, float(params[4]), float(stderr[4])


def fit_scint_params_sspec(acf2d, dt, df, nchan: int, nsub: int,
                           alpha: float | None = _ALPHA_KOLMOGOROV,
                           backend: str = "numpy",
                           steps: int = 20) -> ScintParams:
    """Fit tau/dnu in the Fourier (power-spectrum) domain — the method the
    reference declares but never finishes (``get_scint_params('sspec')``
    stub at dynspec.py:953-957 calling broken models at
    scint_models.py:115-188; both completed here, see
    models.acf_models.*_sspec_model).

    The 1-D ACF cuts are mirrored to symmetric functions and FFT'd exactly
    as the models do, so data and model live on the same spectral grid.
    Low spectral bins carry the scintle signal; the fit weights all bins
    equally, matching the models' construction.
    """
    backend = resolve(backend)
    a = np.asarray(acf2d, dtype=np.float64)
    x_t, y_t, x_f, y_f = acf_cuts(a, dt, abs(df), nchan, nsub, xp=np)
    tau0, dnu0, amp0, wn0 = initial_guesses(x_t, y_t, x_f, y_f, xp=np)

    from ..models.acf_models import mirror_spectrum, scint_sspec_model

    y_spec = np.concatenate([mirror_spectrum(y_t, xp=np),
                             mirror_spectrum(y_f, xp=np)])
    free = alpha is None
    p0 = np.array([float(tau0), float(dnu0), float(amp0), float(wn0)]
                  + ([_ALPHA_KOLMOGOROV] if free else []))
    lo = [1e-10, 1e-10, 0.0, 0.0] + ([0.0] if free else [])
    hi = [np.inf] * 4 + ([8.0] if free else [])

    if backend == "numpy":
        def resid(p):
            a_ = p[4] if free else alpha
            return y_spec - scint_sspec_model(x_t, x_f, p[0], p[1], p[2],
                                              p[3], a_, xp=np)

        res = least_squares_numpy(resid, p0, bounds=(lo, hi))
    else:
        import jax.numpy as jnp

        y_spec_j = jnp.asarray(y_spec)
        x_t_j, x_f_j = jnp.asarray(x_t), jnp.asarray(x_f)

        def resid_j(p, xt, xf, ys):
            a_ = p[4] if free else alpha
            return ys - scint_sspec_model(xt, xf, p[0], p[1], p[2], p[3],
                                          a_, xp=jnp)

        res = lm_fit_jax(resid_j, jnp.asarray(p0),
                         bounds=(jnp.asarray(lo), jnp.asarray(hi)),
                         args=(x_t_j, x_f_j, y_spec_j), steps=steps)
    return _to_scint_params(res, alpha, np)


@functools.lru_cache(maxsize=None)
def _fit_scint_2d_batch_jax(alpha, steps, crop_t, crop_f, nchan, nsub):
    """Batched 2-D ACF fit (tau, dnu, amp, wn, tilt — plus the power-law
    index when ``alpha is None``), vmapped over epochs.

    Windows are cropped from the [B, 2nf, 2nt] ACF batch with static
    bounds; taper scales use the full scan extents (see
    fit_scint_params_2d).
    """
    import jax
    import jax.numpy as jnp

    from ..models.acf_models import scint_acf_model_2d

    free = alpha is None

    def single(win, y_t_full, y_f_full, dt, df):
        x_t, x_f = acf_lags_2d(dt, df, crop_t, crop_f, xp=jnp)
        tmax, fmax = dt * nsub, df * nchan
        # guesses from the FULL-ACF central cuts, exactly as the
        # single-epoch fit_scint_params_2d does (window cuts can clamp
        # tau/dnu guesses at the crop edge for broad scintles)
        nt_, nf_ = y_t_full.shape[-1], y_f_full.shape[-1]
        tau0, dnu0, amp0, wn0 = initial_guesses(
            dt * jnp.linspace(0, nt_, nt_), y_t_full,
            df * jnp.linspace(0, nf_, nf_), y_f_full, xp=jnp)

        def resid(p, w):
            a_ = p[5] if free else alpha
            m = scint_acf_model_2d(x_t, x_f, p[0], p[1], p[2], p[3],
                                   a_, p[4], tmax=tmax, fmax=fmax,
                                   xp=jnp)
            return (w - m).ravel()

        p0 = [tau0, dnu0, amp0, wn0, jnp.zeros_like(tau0)]
        lo = [1e-10, 1e-10, 0.0, 0.0, -jnp.inf]
        hi = [jnp.inf] * 5
        if free:
            p0.append(jnp.full_like(tau0, _ALPHA_KOLMOGOROV))
            lo.append(0.0)
            hi.append(8.0)
        return lm_fit_jax(resid, jnp.stack(p0),
                          bounds=(jnp.array(lo), jnp.array(hi)),
                          args=(win,), steps=steps)

    @jax.jit
    def impl(acf2d_batch, dt, df):
        win = _crop_acf_2d(acf2d_batch, nchan, nsub, crop_t, crop_f)
        y_t_full = acf2d_batch[:, nchan, nsub:]
        y_f_full = acf2d_batch[:, nchan:, nsub]
        res = jax.vmap(single)(win, y_t_full, y_f_full, dt, df)
        sp = ScintParams(
            tau=res.params[:, 0], tauerr=res.stderr[:, 0],
            dnu=res.params[:, 1], dnuerr=res.stderr[:, 1],
            amp=res.params[:, 2], wn=res.params[:, 3],
            talpha=res.params[:, 5] if free else alpha,
            talphaerr=res.stderr[:, 5] if free else None,
            redchi=res.redchi)
        return sp, res.params[:, 4], res.stderr[:, 4]

    return impl


def fit_scint_params_2d_batch(acf2d_batch, dt, df, nchan: int, nsub: int,
                              alpha: float | None = _ALPHA_KOLMOGOROV,
                              crop_frac: float = 0.5, steps: int = 20):
    """Vmapped 2-D ACF fits for a [B, 2nf, 2nt] batch: population-level
    phase-gradient (tilt) statistics in one device program — a capability
    with no reference analogue (its 2-D method is an empty stub).
    ``alpha=None`` frees the power-law index per epoch, as on the
    single-epoch and 1-D paths.

    Returns (ScintParams with [B] leaves, tilt [B], tilterr [B]).
    """
    import jax.numpy as jnp

    crop_t = max(2, int(nsub * crop_frac / 2))
    crop_f = max(2, int(nchan * crop_frac / 2))
    dt = jnp.broadcast_to(jnp.asarray(dt, dtype=jnp.result_type(float)),
                          (acf2d_batch.shape[0],))
    df = jnp.broadcast_to(jnp.asarray(abs(df),
                                      dtype=jnp.result_type(float)),
                          (acf2d_batch.shape[0],))
    return _fit_scint_2d_batch_jax(alpha, int(steps), crop_t, crop_f,
                                   int(nchan), int(nsub))(
        acf2d_batch, dt, df)
